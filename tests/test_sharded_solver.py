"""Sharded single-problem EG solve (shockwave_tpu/solver/eg_sharded.py).

The cross-check contract: counts from the shard_map'd level-set solve on
the 8-virtual-device mesh are BIT-IDENTICAL to the single-device
solve_level's, because both realize the same maximal prefix of the same
(density desc, flat index asc) cell order and every budget sum is exact
in float32 (integer gang sizes x small round counts).
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

import bench
from shockwave_tpu.solver.eg_jax import solve_eg_level, solve_level_counts
from shockwave_tpu.solver.eg_problem import EGProblem
from shockwave_tpu.solver.eg_sharded import (
    solve_eg_level_sharded,
    solve_level_sharded,
)


@pytest.mark.parametrize(
    "num_jobs,future_rounds,num_gpus,seed",
    [(100, 20, 64, 0), (256, 16, 48, 1), (100, 20, 64, 5)],
)
def test_counts_match_single_device(num_jobs, future_rounds, num_gpus, seed):
    p = bench.make_problem(
        num_jobs=num_jobs,
        future_rounds=future_rounds,
        num_gpus=num_gpus,
        seed=seed,
    )
    c_ref, obj_ref = solve_level_counts(p)
    c_sh, obj_sh = solve_level_sharded(p)
    np.testing.assert_array_equal(c_ref, c_sh)
    assert obj_sh == pytest.approx(obj_ref, rel=1e-5)


def test_tie_heavy_identical_jobs():
    """All jobs identical -> every marginal cell density ties; the
    cross-shard tie split must still reproduce the single-device
    flat-index prefix exactly."""
    J = 512
    p = EGProblem(
        priorities=np.full(J, 2.0),
        completed_epochs=np.full(J, 3.0),
        total_epochs=np.full(J, 10.0),
        epoch_duration=np.full(J, 100.0),
        remaining_runtime=np.full(J, 700.0),
        nworkers=np.full(J, 2.0),
        num_gpus=64,
        round_duration=120.0,
        future_rounds=10,
        regularizer=1.0,
        log_bases=np.array([0.0, 0.2, 0.4, 0.6, 0.8, 1.0]),
    )
    c_ref, _ = solve_level_counts(p)
    c_sh, _ = solve_level_sharded(p)
    np.testing.assert_array_equal(c_ref, c_sh)
    # The budget must be saturated up to one gang width (ties split
    # across shards may not waste budget).
    used = float(np.sum(c_sh * p.nworkers))
    budget = float(p.num_gpus * p.future_rounds)
    assert used <= budget + 1e-6
    assert used > budget - 2.0 * np.max(p.nworkers)


def test_mesh_sizes_agree():
    """Same counts from 1-, 2-, 4-, and 8-shard meshes (n=1 exercises the
    degenerate no-partner collective path)."""
    p = bench.make_problem(num_jobs=200, future_rounds=15, num_gpus=64, seed=2)
    ref = None
    for n in (1, 2, 4, 8):
        mesh = Mesh(np.array(jax.devices()[:n]), ("solve",))
        c, _ = solve_level_sharded(p, mesh=mesh)
        if ref is None:
            ref = c
        else:
            np.testing.assert_array_equal(ref, c)


def test_end_to_end_schedule_matches_single_device():
    """solve_eg_level_sharded shares the host polish/placement tail with
    solve_eg_level, so identical counts give the identical schedule."""
    p = bench.make_problem(num_jobs=128, future_rounds=12, num_gpus=32, seed=4)
    Y_ref = solve_eg_level(p)
    Y_sh = solve_eg_level_sharded(p)
    np.testing.assert_array_equal(Y_ref, Y_sh)
    # Feasibility of the sharded schedule on its own terms.
    assert Y_sh.shape == (p.num_jobs, p.future_rounds)
    per_round = (Y_sh * p.nworkers[:, None]).sum(axis=0)
    assert (per_round <= p.num_gpus + 1e-6).all()


@pytest.mark.slow
def test_16k_jobs_cross_check():
    """The SURVEY §5.7 scale claim: one 16,384-job planning problem sharded
    over the 8-device mesh, bit-identical to the single-device solve."""
    p = bench.make_problem(
        num_jobs=16384, future_rounds=50, num_gpus=4096, seed=0
    )
    c_ref, obj_ref = solve_level_counts(p)
    c_sh, obj_sh = solve_level_sharded(p)
    np.testing.assert_array_equal(c_ref, c_sh)
    assert obj_sh == pytest.approx(obj_ref, rel=1e-5)
    # Sanity on the schedule scale itself.
    assert int(c_sh.sum()) > 0
    assert float(np.sum(c_sh * p.nworkers)) <= p.num_gpus * p.future_rounds


def test_sharded_backend_end_to_end_matches_level():
    """shockwave_tpu_sharded is a first-class selectable backend whose
    simulated trace metrics are identical to the single-device level
    backend's (bit-identical counts -> identical schedules)."""
    from shockwave_tpu.core.scheduler import Scheduler
    from shockwave_tpu.data.default_oracle import generate_oracle
    from shockwave_tpu.data.profiles import synthesize_profiles
    from shockwave_tpu.policies import get_available_policies, get_policy
    from tests.test_simulator import tiny_trace

    assert "shockwave_tpu_sharded" in get_available_policies()

    def run(policy_name):
        jobs, arrivals = tiny_trace(num_jobs=5, epochs=2, arrival_gap=30.0)
        oracle = generate_oracle()
        profiles = synthesize_profiles(jobs, oracle)
        sched = Scheduler(
            get_policy(policy_name),
            throughputs=oracle,
            seed=0,
            time_per_iteration=120,
            profiles=profiles,
            shockwave_config={
                "num_gpus": 2,
                "time_per_iteration": 120,
                "future_rounds": 8,
                "lambda": 5.0,
                "k": 10.0,
            },
        )
        makespan = sched.simulate({"v100": 2}, arrivals, jobs)
        return sched, makespan

    sharded, mk_sharded = run("shockwave_tpu_sharded")
    level, mk_level = run("shockwave_tpu_level")
    assert mk_sharded == pytest.approx(mk_level)
    assert len(sharded._job_completion_times) == 5
    for job_id, jct in level._job_completion_times.items():
        assert sharded._job_completion_times[job_id] == pytest.approx(jct)


def test_tpu_backend_auto_dispatches_to_sharded(monkeypatch):
    """The production "tpu" backend routes fleet-scale problems
    (>= SHARDED_DISPATCH_MIN_JOBS, > 1 visible device) to the sharded
    solver BEFORE the native fast path; below the threshold it never
    touches it."""
    import shockwave_tpu.policies.shockwave as sw
    from shockwave_tpu.policies.shockwave import ShockwavePlanner
    from shockwave_tpu.solver import eg_sharded

    calls = []
    real = eg_sharded.solve_eg_level_sharded

    def spy(problem, *a, **kw):
        calls.append(problem.num_jobs)
        return real(problem, *a, **kw)

    monkeypatch.setattr(eg_sharded, "solve_eg_level_sharded", spy)
    monkeypatch.setattr(sw, "SHARDED_DISPATCH_MIN_JOBS", 8)

    planner = ShockwavePlanner(
        {
            "num_gpus": 8,
            "time_per_iteration": 120,
            "future_rounds": 6,
            "lambda": 5.0,
            "k": 10.0,
        },
        backend="tpu",
    )
    small = bench.make_problem(num_jobs=6, future_rounds=6, num_gpus=8)
    planner._solve(small)
    assert calls == [], "sub-threshold problem took the sharded path"

    big = bench.make_problem(num_jobs=32, future_rounds=6, num_gpus=8)
    Y, backend_used = planner._solve(big)
    assert calls == [32], "fleet-scale problem bypassed the sharded path"
    assert backend_used == "sharded"
    assert Y.shape == (32, 6)
    big.audit_schedule(np.asarray(Y))


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_degenerate_problems_match_single_device(seed):
    """Randomized cross-check including the kernel's edge cases: gangs
    wider than the cluster (never schedulable), fully-completed jobs
    (zero remaining work), and FULLY duplicated rows — priorities
    included, so their marginal densities genuinely tie and the
    cross-shard tie split is exercised. Counts must stay bit-identical
    to the single-device solve on every draw.

    J is pinned to one padding class (slots=128) and future_rounds /
    regularizer to two values each so the 6 seeds share compiled
    executables instead of re-jitting per draw."""
    rng = np.random.default_rng(100 + seed)
    J = int(rng.integers(70, 128))
    priorities = rng.uniform(0.1, 40.0, J)
    total = rng.integers(1, 60, J).astype(float)
    completed = np.floor(total * rng.uniform(0, 1.0, J))
    # A slice of jobs is fully complete (no remaining work).
    done = rng.random(J) < 0.15
    completed[done] = total[done]
    epoch_dur = rng.uniform(30, 3000, J)
    nworkers = rng.choice(
        [1, 2, 4, 8, 64], J, p=[0.5, 0.2, 0.15, 0.1, 0.05]
    ).astype(float)
    num_gpus = int(rng.integers(8, 48))  # some 64-wide gangs can't fit
    # Duplicate a block of FULL rows (priorities too): identical rows
    # have identical densities, forcing ties that straddle shards.
    dup = int(rng.integers(0, J - 20))
    block = slice(dup, dup + 10)
    for arr in (priorities, total, completed, epoch_dur, nworkers):
        arr[block] = arr[dup]
    p = EGProblem(
        priorities=priorities,
        completed_epochs=completed,
        total_epochs=total,
        epoch_duration=epoch_dur,
        remaining_runtime=(total - completed) * epoch_dur,
        nworkers=nworkers,
        num_gpus=num_gpus,
        round_duration=120.0,
        future_rounds=int(rng.choice([10, 20])),
        regularizer=float(rng.choice([0.0, 10.0])),
        log_bases=np.array([0.0, 0.2, 0.4, 0.6, 0.8, 1.0]),
    )
    c_ref, obj_ref = solve_level_counts(p)
    c_sh, obj_sh = solve_level_sharded(p)
    np.testing.assert_array_equal(c_ref, c_sh)
    assert obj_sh == pytest.approx(obj_ref, rel=1e-5, abs=1e-6)
    # Too-wide gangs never receive rounds.
    assert not np.any(c_sh[p.nworkers > p.num_gpus] > 0)
