"""Policy-family unit tests: tiny hand-written throughput dicts, golden
allocations, and cross-formulation validity checks (reference test style:
scheduler/tests/policies_tests.py)."""

import numpy as np
import pytest

from shockwave_tpu.core.ids import JobId
from shockwave_tpu.policies import get_policy


def validity(alloc, throughputs, scale_factors, cluster_spec):
    """Base-polytope validity: capacity per type; per-single share <= 1."""
    per_type = {wt: 0.0 for wt in cluster_spec}
    per_single = {}
    for job_id, shares in alloc.items():
        sf = max(scale_factors[s] for s in job_id.singletons())
        for wt, v in shares.items():
            assert v >= -1e-6
            per_type[wt] += v * sf
        for s in job_id.singletons():
            per_single[s] = per_single.get(s, 0.0) + sum(shares.values())
    for wt in per_type:
        assert per_type[wt] <= cluster_spec[wt] + 1e-4, (wt, per_type[wt])
    for s, total in per_single.items():
        assert total <= 1.0 + 1e-4, (s, total)


def simple_throughputs(m=3, v=4.0, k=1.0):
    return {JobId(i): {"v100": v, "k80": k} for i in range(m)}


CLUSTER = {"v100": 2, "k80": 2}


class TestFinishTimeFairness:
    def args(self, m=2):
        tputs = simple_throughputs(m)
        sf = {JobId(i): 1 for i in range(m)}
        pw = {JobId(i): 1.0 for i in range(m)}
        tss = {JobId(i): 100.0 for i in range(m)}
        steps = {JobId(i): 1000 for i in range(m)}
        return tputs, sf, pw, tss, steps, CLUSTER

    def test_identical_jobs_get_equal_allocations(self):
        pol = get_policy("finish_time_fairness_perf")
        tputs, sf, pw, tss, steps, cluster = self.args()
        alloc = pol.get_allocation(tputs, sf, pw, tss, steps, cluster)
        validity(alloc, tputs, sf, cluster)
        a0 = sum(alloc[JobId(0)].values())
        a1 = sum(alloc[JobId(1)].values())
        assert a0 == pytest.approx(a1, abs=0.05)

    def test_base_variant_uses_v100_throughputs(self):
        pol = get_policy("finish_time_fairness")
        tputs, sf, pw, tss, steps, cluster = self.args()
        alloc = pol.get_allocation(tputs, sf, pw, tss, steps, cluster)
        validity(alloc, tputs, sf, cluster)

    def test_packed_variant(self):
        pol = get_policy("finish_time_fairness_packed")
        m = 2
        tputs = simple_throughputs(m)
        tputs[JobId(0, 1)] = {"v100": [2.5, 2.5], "k80": [0.6, 0.6]}
        sf = {JobId(i): 1 for i in range(m)}
        pw = {JobId(i): 1.0 for i in range(m)}
        tss = {JobId(i): 100.0 for i in range(m)}
        steps = {JobId(i): 1000 for i in range(m)}
        alloc = pol.get_allocation(tputs, sf, pw, tss, steps, CLUSTER)
        validity(alloc, tputs, sf, CLUSTER)

    def test_state_accumulates_between_rounds(self):
        pol = get_policy("finish_time_fairness_perf")
        tputs, sf, pw, tss, steps, cluster = self.args()
        pol.get_allocation(tputs, sf, pw, tss, steps, cluster)
        steps2 = {j: s - 100 for j, s in steps.items()}
        pol.get_allocation(tputs, sf, pw, tss, steps2, cluster)
        assert all(v > 0 for v in pol._cumulative_isolated_time.values())


class TestMinTotalDuration:
    def test_fast_jobs_finish_within_bound(self):
        pol = get_policy("min_total_duration_perf")
        tputs = simple_throughputs(2)
        sf = {JobId(i): 1 for i in range(2)}
        steps = {JobId(i): 1000 for i in range(2)}
        alloc = pol.get_allocation(tputs, sf, steps, CLUSTER)
        validity(alloc, tputs, sf, CLUSTER)
        # 2 jobs, 2 v100s at 4 steps/s: both can run flat out on v100;
        # each job's effective rate should be ~4 steps/s.
        for i in range(2):
            rate = sum(
                tputs[JobId(i)][wt] * alloc[JobId(i)][wt] for wt in CLUSTER
            )
            assert rate >= 3.0

    def test_packed_variant_valid(self):
        pol = get_policy("min_total_duration_packed")
        tputs = simple_throughputs(2)
        tputs[JobId(0, 1)] = {"v100": [2.5, 2.5], "k80": [0.6, 0.6]}
        sf = {JobId(i): 1 for i in range(2)}
        steps = {JobId(i): 1000 for i in range(2)}
        alloc = pol.get_allocation(tputs, sf, steps, CLUSTER)
        validity(alloc, tputs, sf, CLUSTER)


class TestMaxSumThroughput:
    def test_capacity_flows_to_fastest_jobs(self):
        pol = get_policy("max_sum_throughput_perf")
        tputs = {
            JobId(0): {"v100": 10.0, "k80": 1.0},
            JobId(1): {"v100": 1.0, "k80": 0.5},
            JobId(2): {"v100": 1.0, "k80": 0.5},
        }
        sf = {JobId(i): 1 for i in range(3)}
        alloc = pol.get_allocation(tputs, sf, CLUSTER)
        validity(alloc, tputs, sf, CLUSTER)
        # The throughput-sum objective must saturate job 0 on a v100.
        assert alloc[JobId(0)]["v100"] == pytest.approx(1.0, abs=1e-4)

    def test_slo_constraint_reserves_rate(self):
        pol = get_policy("max_sum_throughput_normalized_by_cost_perf_SLOs")
        tputs = {
            JobId(0): {"v100": 10.0, "k80": 1.0},
            JobId(1): {"v100": 1.0, "k80": 0.5},
        }
        sf = {JobId(i): 1 for i in range(2)}
        cluster = {"v100": 1, "k80": 0}
        alloc = pol.get_allocation(
            tputs,
            sf,
            cluster,
            SLOs={JobId(1): 2000.0},
            num_steps_remaining={JobId(0): 1000, JobId(1): 1000},
        )
        validity(alloc, tputs, sf, cluster)
        # Job 1 needs 1000 steps in 2000s => rate 0.5 => half the v100.
        assert alloc[JobId(1)]["v100"] >= 0.5 - 1e-4

    def test_infeasible_slos_dropped(self):
        pol = get_policy("max_sum_throughput_normalized_by_cost_perf_SLOs")
        tputs = {JobId(0): {"v100": 1.0}}
        sf = {JobId(0): 1}
        alloc = pol.get_allocation(
            tputs,
            sf,
            {"v100": 1},
            SLOs={JobId(0): 1.0},  # 1e6 steps in 1s: impossible
            num_steps_remaining={JobId(0): 10**6},
        )
        assert alloc is not None


class TestAllox:
    def test_jobs_assigned_to_best_workers(self):
        pol = get_policy("allox")
        tputs = {
            JobId(0): {"v100": 10.0, "k80": 1.0},
            JobId(1): {"v100": 10.0, "k80": 5.0},
        }
        sf = {JobId(i): 1 for i in range(2)}
        tss = {JobId(0): 200.0, JobId(1): 100.0}
        steps = {JobId(i): 1000 for i in range(2)}
        alloc = pol.get_allocation(
            tputs, sf, tss, steps, {"v100": 1, "k80": 1}
        )
        validity(alloc, tputs, sf, {"v100": 1, "k80": 1})
        # Two workers, two jobs: both should be running somewhere.
        placed = sum(1 for j in alloc if sum(alloc[j].values()) > 0.99)
        assert placed == 2
        # Job 1 gains 5x on k80 vs job 0's 1x, so job 0 takes the v100.
        assert alloc[JobId(0)]["v100"] == 1.0
        assert alloc[JobId(1)]["k80"] == 1.0

    def test_rejects_multi_gpu_jobs(self):
        pol = get_policy("allox")
        with pytest.raises(ValueError):
            pol.get_allocation(
                {JobId(0): {"v100": 1.0}},
                {JobId(0): 2},
                {JobId(0): 0.0},
                {JobId(0): 100},
                {"v100": 2},
            )


class TestGandiva:
    def test_undersubscribed_no_packing(self):
        pol = get_policy("gandiva")
        tputs = simple_throughputs(2)
        tputs[JobId(0, 1)] = {"v100": [2.0, 2.0], "k80": [0.5, 0.5]}
        sf = {JobId(i): 1 for i in range(2)}
        alloc = pol.get_allocation(tputs, sf, CLUSTER)
        validity(alloc, tputs, sf, CLUSTER)
        assert sum(alloc[JobId(0, 1)].values()) == 0.0

    def test_oversubscribed_packs_jobs(self):
        pol = get_policy("gandiva")
        m = 6
        tputs = simple_throughputs(m)
        for i in range(m):
            for j in range(i + 1, m):
                tputs[JobId(i, j)] = {"v100": [3.0, 3.0], "k80": [0.8, 0.8]}
        sf = {JobId(i): 1 for i in range(m)}
        alloc = pol.get_allocation(tputs, sf, CLUSTER)
        validity(alloc, tputs, sf, CLUSTER)
        packed_share = sum(
            sum(alloc[j].values()) for j in alloc if j.is_pair
        )
        assert packed_share > 0.0


class TestWaterFilling:
    def test_equal_jobs_equal_levels(self):
        pol = get_policy("max_min_fairness_water_filling_perf")
        tputs = simple_throughputs(4)
        sf = {JobId(i): 1 for i in range(4)}
        pw = {JobId(i): 1.0 for i in range(4)}
        alloc = pol.get_allocation(tputs, sf, pw, CLUSTER)
        validity(alloc, tputs, sf, CLUSTER)
        shares = [sum(alloc[JobId(i)].values()) for i in range(4)]
        assert max(shares) - min(shares) < 0.05

    def test_water_filling_improves_unsaturated_jobs(self):
        # One job is rate-limited by its own share cap (sum_w x <= 1); the
        # others should rise ABOVE the plain max-min level.
        pol = get_policy("max_min_fairness_water_filling_perf")
        tputs = {
            JobId(0): {"v100": 1.0},
            JobId(1): {"v100": 10.0},
            JobId(2): {"v100": 10.0},
        }
        sf = {JobId(i): 1 for i in range(3)}
        pw = {JobId(i): 1.0 for i in range(3)}
        cluster = {"v100": 3}
        alloc = pol.get_allocation(tputs, sf, pw, cluster)
        validity(alloc, tputs, sf, cluster)
        # Job 0 saturates at share 1. Remaining 2 v100s split between jobs
        # 1 and 2: they should each get ~1 full v100, not be held at the
        # bottleneck level.
        assert sum(alloc[JobId(1)].values()) > 0.9
        assert sum(alloc[JobId(2)].values()) > 0.9

    def test_entity_fairness_reweighting(self):
        pol = get_policy("max_min_fairness_water_filling_perf")
        pol._priority_reweighting_policies = {0: "fairness", 1: "fairness"}
        tputs = simple_throughputs(3)
        sf = {JobId(i): 1 for i in range(3)}
        pw = {JobId(i): 1.0 for i in range(3)}
        alloc = pol.get_allocation(
            tputs,
            sf,
            pw,
            CLUSTER,
            entity_weights={0: 1.0, 1: 1.0},
            entity_to_job_mapping={0: [JobId(0)], 1: [JobId(1), JobId(2)]},
        )
        validity(alloc, tputs, sf, CLUSTER)
        # Entity 0 (one job) should get at least as much as each of entity
        # 1's two jobs individually.
        assert (
            sum(alloc[JobId(0)].values())
            >= sum(alloc[JobId(1)].values()) - 0.05
        )

    def test_hierarchical_mixed_policy_stress(self):
        """10 entities with randomly mixed fifo/fairness reweighting over
        300 jobs on a 3x64 heterogeneous cluster — the reference's
        hierarchical stress (reference:
        scheduler/tests/water_filling_tests_hierarchical.py:14-89) with
        level/saturation invariants and a runtime bound added."""
        import random
        import time

        random.seed(0)
        num_entities, num_jobs = 10, 300
        worker_types = ["k80", "p100", "v100"]
        cluster = {wt: 64 for wt in worker_types}
        prp, e2j, ew, pw, tputs, sf = {}, {}, {}, {}, {}, {}
        for i in range(num_entities):
            ent = f"entity{i}"
            prp[ent] = ["fifo", "fairness"][random.randint(0, 1)]
            e2j[ent] = []
            ew[ent] = random.randint(1, 5)
        for i in range(num_jobs):
            ths = sorted(random.random() for _ in worker_types)
            tputs[JobId(i)] = dict(zip(worker_types, ths))
            sf[JobId(i)] = 2 ** random.randint(0, 2)
            ent = f"entity{random.randint(0, num_entities - 1)}"
            w = random.randint(1, 5)
            if prp[ent] == "fifo":
                w = 1.0
            pw[JobId(i)] = w
            e2j[ent].append(JobId(i))

        pol = get_policy("max_min_fairness_water_filling_perf")
        pol._priority_reweighting_policies = prp
        t0 = time.time()
        alloc = pol.get_allocation(
            tputs, sf, pw, cluster,
            entity_weights=ew, entity_to_job_mapping=e2j,
        )
        wall = time.time() - t0
        # Generous bound (24x the ~5 s local runtime): catches a return
        # to the pre-dual-filter O(jobs) probes per round (~80 s) without
        # flaking on a loaded host.
        assert wall < 120.0, f"water filling took {wall:.1f}s"
        assert set(alloc) == set(tputs)
        validity(alloc, tputs, sf, cluster)
        # Saturation invariant: with 561 workers requested and only 192
        # available, every worker must be in use (no idle capacity left
        # behind by the level raises).
        for wt in worker_types:
            used = sum(alloc[j][wt] * sf[j] for j in alloc)
            assert used > 64 * 0.98, (wt, used)
        # Entity-policy invariant: within each fifo entity the earliest
        # job is the active one, so it receives at least as much total
        # time share as any later job in the same entity.
        for ent, jobs in e2j.items():
            if prp[ent] != "fifo" or len(jobs) < 2:
                continue
            first = min(jobs)
            first_share = sum(alloc[first].values())
            for j in jobs:
                if j != first:
                    assert (
                        first_share >= sum(alloc[j].values()) - 0.05
                    ), (ent, first, j)

    def test_packed_variant_valid(self):
        pol = get_policy("max_min_fairness_water_filling_packed")
        tputs = simple_throughputs(2)
        tputs[JobId(0, 1)] = {"v100": [2.5, 2.5], "k80": [0.6, 0.6]}
        sf = {JobId(i): 1 for i in range(2)}
        pw = {JobId(i): 1.0 for i in range(2)}
        alloc = pol.get_allocation(tputs, sf, pw, CLUSTER)
        validity(alloc, tputs, sf, CLUSTER)


class TestStrategyProof:
    def test_returns_allocation_and_discounts(self):
        pol = get_policy("max_min_fairness_strategy_proof")
        tputs = simple_throughputs(3)
        sf = {JobId(i): 1 for i in range(3)}
        pw = {JobId(i): 1.0 for i in range(3)}
        alloc, discounts = pol.get_allocation(tputs, sf, pw, CLUSTER)
        validity(alloc, tputs, sf, CLUSTER)
        assert len(discounts) == 3
        # Identical jobs -> identical discounts; discounts near <= 1.
        assert np.allclose(discounts, discounts[0], rtol=0.05)
        assert np.all(discounts <= 1.05)


class TestMaxMinFairnessPacked:
    def test_beneficial_packing_used(self):
        pol = get_policy("max_min_fairness_packed")
        m = 4
        tputs = {JobId(i): {"v100": 4.0} for i in range(m)}
        for i in range(m):
            for j in range(i + 1, m):
                # Packing is nearly free: each gets 90% of isolated.
                tputs[JobId(i, j)] = {"v100": [3.6, 3.6]}
        sf = {JobId(i): 1 for i in range(m)}
        pw = {JobId(i): 1.0 for i in range(m)}
        cluster = {"v100": 2}
        alloc = pol.get_allocation(tputs, sf, pw, cluster)
        validity(alloc, tputs, sf, cluster)
        packed_share = sum(
            sum(alloc[j].values()) for j in alloc if j.is_pair
        )
        assert packed_share > 0.5

    def test_agrees_with_unpacked_when_packing_useless(self):
        pol_packed = get_policy("max_min_fairness_packed")
        pol_plain = get_policy("max_min_fairness_perf")
        m = 3
        tputs_plain = {JobId(i): {"v100": 4.0} for i in range(m)}
        tputs = dict(tputs_plain)
        for i in range(m):
            for j in range(i + 1, m):
                tputs[JobId(i, j)] = {"v100": [0.0, 0.0]}
        sf = {JobId(i): 1 for i in range(m)}
        pw = {JobId(i): 1.0 for i in range(m)}
        cluster = {"v100": 2}
        alloc_packed = pol_packed.get_allocation(tputs, sf, pw, cluster)
        alloc_plain = pol_plain.get_allocation(tputs_plain, sf, pw, cluster)
        validity(alloc_packed, tputs, sf, cluster)
        for i in range(m):
            assert sum(alloc_packed[JobId(i)].values()) == pytest.approx(
                sum(alloc_plain[JobId(i)].values()), abs=0.05
            )


def test_slo_pruning_keeps_meetable_deadlines_enforceable():
    """A doomed job (deadline unreachable even at full share) must not
    disable SLO steering for jobs whose deadlines are still meetable —
    the reference re-solves with ALL SLOs dropped on any infeasibility
    (reference: policies/max_sum_throughput.py:91-96), so one doomed
    job starves every other deadline there."""
    from shockwave_tpu.policies import get_policy

    pol = get_policy("max_sum_throughput_normalized_by_cost_perf_SLOs")
    throughputs = {0: {"v100": 10.0}, 1: {"v100": 1.0}}
    scale_factors = {0: 1, 1: 1}
    cluster = {"v100": 1}

    # Unconstrained max-throughput starves the slow job entirely.
    a = pol.get_allocation(throughputs, scale_factors, cluster)
    assert a[1]["v100"] < 1e-6

    # A feasible deadline (needs an 0.8 time share) must be honored.
    a = pol.get_allocation(
        throughputs, scale_factors, cluster,
        SLOs={1: 100.0}, num_steps_remaining={1: 80.0},
    )
    assert a[1]["v100"] >= 0.8 - 1e-6

    # Adding a doomed job must not drop job 1's constraint.
    throughputs[2] = {"v100": 1.0}
    scale_factors[2] = 1
    a = pol.get_allocation(
        throughputs, scale_factors, cluster,
        SLOs={1: 100.0, 2: 1.0}, num_steps_remaining={1: 80.0, 2: 1e9},
    )
    assert a[1]["v100"] >= 0.8 - 1e-6
    assert a[2]["v100"] < 1e-6


def test_slo_pruning_accounts_for_scale_factor_capacity():
    """The reachability bound must include the capacity cap: a gang job
    whose scale factor exceeds the cluster can only get
    num_workers/scale_factor of a time share, so a deadline feasible at
    x=1 but not at that cap is doomed and must be pruned (not left in
    to make the LP infeasible and drop everyone's SLOs)."""
    from shockwave_tpu.policies import get_policy

    pol = get_policy("max_sum_throughput_normalized_by_cost_perf_SLOs")
    throughputs = {0: {"v100": 10.0}, 1: {"v100": 1.0}, 2: {"v100": 10.0}}
    scale_factors = {0: 1, 1: 1, 2: 4}  # job 2 wants 4 of the 2 GPUs
    cluster = {"v100": 2}
    a = pol.get_allocation(
        throughputs, scale_factors, cluster,
        # job 2's required rate 8 < its raw max 10, but its capacity-
        # capped max is 10 * (2/4) = 5 -> doomed, must be pruned so
        # job 1's meetable deadline stays enforced.
        SLOs={1: 100.0, 2: 1.0},
        num_steps_remaining={1: 80.0, 2: 8.0},
    )
    assert a[1]["v100"] >= 0.8 - 1e-6


def test_slo_bound_allows_multi_type_splitting():
    """The reachability bound must price a time share split across
    worker types: required rate 9.3 is unreachable on either type alone
    under the caps (v100 capped at 0.5 by the gang size) but reachable
    with x=(0.5, 0.5) -> 10*0.5 + 9*0.5 = 9.5, so the constraint must
    be kept and enforced."""
    from shockwave_tpu.policies import get_policy

    pol = get_policy("max_sum_throughput_normalized_by_cost_perf_SLOs")
    throughputs = {
        0: {"v100": 10.0, "p100": 9.0},
        1: {"v100": 100.0, "p100": 1.0},
    }
    scale_factors = {0: 2, 1: 1}
    cluster = {"v100": 1, "p100": 2}
    a = pol.get_allocation(
        throughputs, scale_factors, cluster,
        SLOs={0: 10.0}, num_steps_remaining={0: 93.0},
    )
    rate = 10.0 * a[0]["v100"] + 9.0 * a[0]["p100"]
    assert rate >= 9.3 - 1e-6, a


def test_packed_slo_policy_runs_and_prunes():
    """The packed SLO variant must run (regression: its capacity-cap
    expression once indexed a plain list with [None, :]) and apply the
    same doomed-deadline pruning as the unpacked one."""
    from shockwave_tpu.core.ids import JobId
    from shockwave_tpu.policies import get_policy

    pol = get_policy("max_sum_throughput_normalized_by_cost_packed_SLOs")
    throughputs = {
        JobId(0): {"v100": 10.0},
        JobId(1): {"v100": 1.0},
    }
    scale_factors = {JobId(0): 1, JobId(1): 1}
    cluster = {"v100": 1}
    # No SLOs: must simply run.
    a = pol.get_allocation(throughputs, scale_factors, cluster)
    assert a is not None
    # A doomed deadline must not disable job 1's meetable one.
    a = pol.get_allocation(
        throughputs, scale_factors, cluster,
        SLOs={JobId(1): 100.0, JobId(0): 1.0},
        num_steps_remaining={JobId(1): 80.0, JobId(0): 1e9},
    )
    assert a[JobId(1)]["v100"] >= 0.8 - 1e-6, a
