"""Heterogeneous Shockwave: one EG plan per worker-type pool.

BEYOND REFERENCE: the reference's Shockwave plans a single homogeneous
pool and idles every other worker type (reference
scheduler/scheduler.py:991-1014). Here a mixed cluster upgrades the
planner to a PoolSetPlanner at first admission — each pool plans and
runs its own jobs with profile durations rescaled to its measured
speed.
"""

import os

import pytest

from shockwave_tpu.core.scheduler import Scheduler
from shockwave_tpu.data.default_oracle import generate_oracle
from shockwave_tpu.data.profiles import synthesize_profiles
from shockwave_tpu.policies import get_policy
from shockwave_tpu.policies.shockwave import PoolSetPlanner, ShockwavePlanner
from tests.test_simulator import tiny_trace


def run_hetero(cluster, num_jobs=8, hetero_pools=True, num_gpus=None, **kw):
    # Simultaneous arrivals: with live-load balancing an uncontended
    # cluster correctly routes everything to the fastest pool, so the
    # multi-pool behavior only shows under contention.
    jobs, arrivals = tiny_trace(
        num_jobs=num_jobs, epochs=3, arrival_gap=0.0
    )
    oracle = generate_oracle()
    profiles = synthesize_profiles(jobs, oracle)
    sched = Scheduler(
        get_policy("shockwave_tpu"),
        throughputs=oracle,
        seed=0,
        time_per_iteration=120,
        profiles=profiles,
        shockwave_config={
            # With hetero pools each child gets its own pool size; the
            # single-planner (reference-parity) mode must be configured
            # with the PLANNED pool's size, as the reference configs are.
            "num_gpus": (
                num_gpus if num_gpus is not None
                else (
                    sum(cluster.values()) if hetero_pools
                    else cluster.get("v100", next(iter(cluster.values())))
                )
            ),
            "time_per_iteration": 120,
            "future_rounds": 8,
            "lambda": 5.0,
            "k": 10.0,
            "hetero_pools": hetero_pools,
        },
    )
    makespan = sched.simulate(dict(cluster), list(arrivals), list(jobs), **kw)
    return sched, makespan


def test_multi_type_cluster_plans_every_pool():
    sched, makespan = run_hetero({"v100": 2, "k80": 2})
    assert isinstance(sched._shockwave, PoolSetPlanner)
    assert set(sched._shockwave.pools) == {"v100", "k80"}
    # Every job completed...
    assert len(sched._job_completion_times) == 8
    assert all(
        t is not None for t in sched._job_completion_times.values()
    )
    # ...and BOTH pools actually executed work — the reference-parity
    # behavior would have left the k80 pool idle. (Completed jobs are
    # removed from the planner, so the durable witnesses are the
    # cumulative admission counts and the per-type busy time.)
    assignments = sched._shockwave.assignments
    assert all(n > 0 for n in assignments.values()), assignments
    per_type_busy = dict(sched._worker_time_so_far)
    assert per_type_busy.get("k80", 0) > 0, per_type_busy
    assert per_type_busy.get("v100", 0) > 0, per_type_busy
    # The load-balanced assignment favors the ~4.5x-faster v100 pool.
    assert assignments["v100"] >= assignments["k80"]
    assert makespan > 0


def test_wide_gangs_never_assigned_to_narrow_pool():
    """A scale_factor-2 gang must not land in a 1-chip pool (whose EG
    solver could never place it — the run would silently end with the
    job unrun)."""
    jobs, arrivals = tiny_trace(
        num_jobs=6, epochs=2, arrival_gap=60.0,
        scale_factors=[2, 2, 2, 2, 2, 2],
    )
    oracle = generate_oracle()
    profiles = synthesize_profiles(jobs, oracle)
    sched = Scheduler(
        get_policy("shockwave_tpu"),
        throughputs=oracle,
        seed=0,
        time_per_iteration=120,
        profiles=profiles,
        shockwave_config={
            "num_gpus": 5,
            "time_per_iteration": 120,
            "future_rounds": 8,
            "lambda": 5.0,
            "k": 10.0,
            "hetero_pools": True,
        },
    )
    sched.simulate({"v100": 4, "k80": 1}, list(arrivals), list(jobs))
    assert len(sched._job_completion_times) == 6
    assert all(
        t is not None for t in sched._job_completion_times.values()
    )
    assert sched._shockwave.assignments.get("k80", 0) == 0


def test_single_type_cluster_keeps_single_planner():
    sched, _ = run_hetero({"v100": 2})
    assert isinstance(sched._shockwave, ShockwavePlanner)


def test_flag_off_keeps_reference_parity_on_mixed_cluster():
    """Without "hetero_pools": true the reference behavior stands: the
    single planner plans the v100 pool only, other types idle."""
    sched, _ = run_hetero({"v100": 2, "k80": 2}, hetero_pools=False)
    assert isinstance(sched._shockwave, ShockwavePlanner)
    assert float(sched._worker_time_so_far.get("k80", 0.0)) == 0.0


def test_hetero_beats_idle_pool_parity_on_reference_trace():
    """The whole point: on the SAME mixed cluster, planning every pool
    must beat the reference behavior of planning only the v100 pool and
    idling the rest. Measured on the reference's 120-job trace
    (8xv100 + 4xp100 + 4xk80): makespan 46,021 -> 35,980 s."""
    trace = (
        "/root/reference/scheduler/traces/shockwave/"
        "120_0.2_5_100_40_25_0,0.5,0.5_0.6,0.3,0.09,0.01"
        "_multigpu_dynamic.trace"
    )
    if not os.path.exists(trace):
        pytest.skip("reference trace not mounted")
    from shockwave_tpu.data import load_or_synthesize_profiles, parse_trace

    def run(hetero_pools):
        jobs, arrivals = parse_trace(trace)
        oracle = generate_oracle()
        profiles = load_or_synthesize_profiles(
            trace, jobs, oracle, cache=False
        )
        for i, job in enumerate(jobs):
            job.duration = sum(profiles[i]["duration_every_epoch"])
        sched = Scheduler(
            get_policy("shockwave_tpu"),
            throughputs=oracle,
            seed=0,
            time_per_iteration=120,
            profiles=profiles,
            shockwave_config={
                "num_gpus": 16 if hetero_pools else 8,
                "time_per_iteration": 120,
                "future_rounds": 20,
                "lambda": 5.0,
                "k": 10.0,
                "hetero_pools": hetero_pools,
            },
        )
        return sched.simulate(
            {"v100": 8, "p100": 4, "k80": 4}, list(arrivals), list(jobs)
        )

    mk_hetero = run(True)
    mk_parity = run(False)
    assert mk_hetero < mk_parity, (mk_hetero, mk_parity)


def test_hetero_checkpoint_resume(tmp_path):
    """The PoolSetPlanner state (children + job->pool map + assignment
    load) round-trips through the simulator checkpoint."""
    ckpt = str(tmp_path / "hetero.ckpt")
    ref, mk_ref = run_hetero({"v100": 2, "k80": 2})
    a, mk_a = run_hetero(
        {"v100": 2, "k80": 2}, checkpoint_threshold=4, checkpoint_file=ckpt
    )
    assert os.path.exists(ckpt)
    assert mk_a == pytest.approx(mk_ref)
    b, mk_b = run_hetero({"v100": 2, "k80": 2}, checkpoint_file=ckpt)
    assert mk_b == pytest.approx(mk_ref)
    assert isinstance(b._shockwave, PoolSetPlanner)
    for job_id, jct in ref._job_completion_times.items():
        assert b._job_completion_times[job_id] == pytest.approx(jct)
