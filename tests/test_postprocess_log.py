"""The structured round log: recorded by the simulator, summarized by
the postprocess tool, and round-trippable back into a parseable trace
(capability of reference: scripts/utils/postprocess_simulator_log.py and
generate_trace_from_scheduler_log.py)."""

import importlib.util
import os

import pytest

from tests.test_simulator import run_sim, tiny_trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_postprocess():
    spec = importlib.util.spec_from_file_location(
        "postprocess_log",
        os.path.join(REPO, "scripts", "analysis", "postprocess_log.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def sim_log(tmp_path_factory):
    jobs, arrivals = tiny_trace(num_jobs=5, epochs=2, arrival_gap=60.0)
    sched, makespan = run_sim("max_min_fairness", jobs, arrivals)
    path = tmp_path_factory.mktemp("logs") / "run.jsonl"
    sched.save_round_log(str(path))
    return str(path), jobs, arrivals, sched


def test_round_log_events_complete(sim_log):
    path, jobs, arrivals, sched = sim_log
    pp = _load_postprocess()
    events = pp.load_events(path)
    kinds = {e["event"] for e in events}
    assert kinds == {"job", "round", "complete"}
    assert sum(e["event"] == "job" for e in events) == len(jobs)
    assert sum(e["event"] == "complete" for e in events) == len(jobs)
    assert (
        sum(e["event"] == "round" for e in events)
        == sched._num_completed_rounds
    )


def test_per_job_table(sim_log):
    path, jobs, arrivals, _ = sim_log
    pp = _load_postprocess()
    rows = pp.per_job_table(pp.load_events(path))
    assert len(rows) == len(jobs)
    for row, arrival in zip(rows, arrivals):
        assert row["arrival"] == pytest.approx(arrival)
        assert row["rounds_run"] > 0
        assert row["completion_time"] is not None
        assert row["queueing_delay"] is not None
        assert row["queueing_delay"] >= 0


def test_per_round_occupancy(sim_log):
    path, _, _, _ = sim_log
    pp = _load_postprocess()
    occ = pp.per_round_occupancy(pp.load_events(path), num_gpus=4)
    assert occ
    # Idle rounds (arrival gaps) legitimately record zero busy GPUs.
    assert all(0 <= r["gpus_busy"] <= 4 for r in occ)
    assert all(0 <= r["utilization"] <= 1.0 for r in occ)
    assert any(r["gpus_busy"] > 0 for r in occ)


def test_emit_trace_round_trips(sim_log, tmp_path):
    from shockwave_tpu.data.trace import parse_trace

    path, jobs, arrivals, _ = sim_log
    pp = _load_postprocess()
    out = tmp_path / "regenerated.trace"
    n = pp.emit_trace(pp.load_events(path), str(out))
    assert n == len(jobs)
    re_jobs, re_arrivals = parse_trace(str(out))
    assert [j.job_type for j in re_jobs] == [j.job_type for j in jobs]
    assert [j.scale_factor for j in re_jobs] == [
        j.scale_factor for j in jobs
    ]
    assert re_arrivals == pytest.approx(arrivals)
