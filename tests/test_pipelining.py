"""Plan-ahead pipelining: speculative next-round solves
(shockwave_tpu/policies/speculation.py) and their boundary reconcile.

The contract under test:

* no-churn speculative plans are BIT-IDENTICAL to the serial boundary
  solve (sim prediction is exact), for both the flat planner and the
  cell federation;
* churn between snapshot and boundary (arrival / departure / progress
  drift / capacity) reconciles as a repair or miss, never loses a job,
  and never re-plans more eagerly than the serial scheduler;
* speculative and repaired rounds replay bit-exact from the flight
  recorder (speculative records are overlays — their predicted
  throughput tails must not corrupt the live delta encoding);
* the Dirichlet change-point reweight closes the remaining-runtime
  error on jobs whose measured batch-size switch contradicts the
  profile pattern.
"""

import os

import pytest

from shockwave_tpu import obs
from shockwave_tpu.core.scheduler import Scheduler
from shockwave_tpu.data.default_oracle import generate_oracle
from shockwave_tpu.data.generate import smoke_trace_jobs
from shockwave_tpu.data.profiles import synthesize_profiles
from shockwave_tpu.policies import get_policy
from shockwave_tpu.policies.shockwave import ShockwavePlanner
from shockwave_tpu.policies.speculation import (
    SpecOutcome,
    diff_fingerprints,
    planner_fingerprint,
)

ORACLE = generate_oracle()


def make_profile(bs_every_epoch, duration_every_epoch, nsamples=1000):
    n = len(bs_every_epoch)
    return {
        "num_epochs": n,
        "num_samples_per_epoch": nsamples,
        "scale_factor": 1,
        "duration": float(sum(duration_every_epoch)),
        "bs_every_epoch": list(bs_every_epoch),
        "mem_every_epoch": [0.0] * n,
        "util_every_epoch": [0.0] * n,
        "duration_every_epoch": list(duration_every_epoch),
    }


def make_jobs(num_jobs=6, epochs=2, arrival_gap=0.0):
    return smoke_trace_jobs(num_jobs, epochs, arrival_gap)


def run_sim(speculate, arrival_gap=0.0, policy="shockwave_tpu_pdhg",
            cells=None, log=None):
    obs.reset()
    if log:
        if os.path.exists(log):
            os.remove(log)
        obs.configure_recorder(log)
    jobs, arrivals = make_jobs(arrival_gap=arrival_gap)
    profiles = synthesize_profiles(jobs, ORACLE)
    config = {
        "num_gpus": 4,
        "time_per_iteration": 120,
        "future_rounds": 6,
        "lambda": 2.0,
        "k": 1e-3,
        "speculate": speculate,
    }
    if cells:
        config["cells"] = cells
    sched = Scheduler(
        get_policy(policy),
        throughputs=ORACLE,
        seed=0,
        time_per_iteration=120,
        profiles=profiles,
        shockwave_config=config,
    )
    makespan = sched.simulate({"v100": 4}, arrivals, jobs)
    if log:
        obs.get_recorder().close()
    return sched, makespan


def round_log(sched):
    return [r for r in sched._round_log if r["event"] == "round"]


# ----------------------------------------------------------------------
# End-to-end: no-churn bit-identity, churn reconcile, replay.
# ----------------------------------------------------------------------
class TestNoChurnBitIdentity:
    def test_flat_planner_identical_to_serial(self):
        serial, mk0 = run_sim(False)
        pipelined, mk1 = run_sim(True)
        assert mk1 == mk0
        assert round_log(pipelined) == round_log(serial)
        stats = pipelined._shockwave.spec_stats
        assert stats["hit"] >= 1
        assert stats["repair"] == 0 and stats["miss"] == 0
        # The hidden solves replaced the serial boundary bill: installed
        # speculative records are tagged in the solve history.
        assert len(pipelined._shockwave.solve_records) == len(
            serial._shockwave.solve_records
        )

    def test_cells_identical_to_serial(self):
        serial, mk0 = run_sim(False, policy="shockwave_tpu_cells", cells=2)
        pipelined, mk1 = run_sim(True, policy="shockwave_tpu_cells", cells=2)
        assert mk1 == mk0
        assert round_log(pipelined) == round_log(serial)
        assert pipelined._shockwave.spec_stats["hit"] >= 1


class TestReconcileUnderChurn:
    def test_arrivals_repair_or_miss_and_lose_nothing(self):
        serial, _ = run_sim(False, arrival_gap=60.0)
        pipelined, _ = run_sim(True, arrival_gap=60.0)
        stats = pipelined._shockwave.spec_stats
        assert stats["repair"] + stats["miss"] >= 1
        completed = sum(
            1
            for t in pipelined._job_completion_times.values()
            if t is not None
        )
        assert completed == 6
        # Never more eager than serial: every live solve the pipelined
        # run pays, the serial run pays too.
        assert len(pipelined._shockwave.solve_records) <= len(
            serial._shockwave.solve_records
        )

    def test_repair_solves_are_tagged(self):
        pipelined, _ = run_sim(True, arrival_gap=60.0)
        stats = pipelined._shockwave.spec_stats
        repairs = [
            r
            for r in pipelined._shockwave.solve_records
            if r.get("repair")
        ]
        assert len(repairs) == stats["repair"]
        assert all(r["backend"] == "pdhg" for r in repairs)


class TestReplayExactness:
    def test_flat_log_replays_speculative_and_repaired_rounds(self, tmp_path):
        from shockwave_tpu.obs.recorder import replay_log, summarize_log

        log = str(tmp_path / "decisions.jsonl")
        pipelined, _ = run_sim(True, arrival_gap=60.0, log=log)
        summary = summarize_log(log)
        assert summary["speculative_plans"] >= 1
        assert summary["speculations"].get("hit", 0) >= 1
        results = replay_log(log)
        assert results
        assert all(not r["diff"] for r in results)

    def test_cells_log_replays_exactly(self, tmp_path):
        from shockwave_tpu.obs.recorder import replay_log

        log = str(tmp_path / "cells.jsonl")
        run_sim(
            True, arrival_gap=60.0, policy="shockwave_tpu_cells",
            cells=2, log=log,
        )
        results = replay_log(log)
        assert results
        assert all(not r["diff"] for r in results)


# ----------------------------------------------------------------------
# Unit: fingerprints and the reconcile state machine.
# ----------------------------------------------------------------------
def make_planner(num_jobs=3, num_gpus=4, **config):
    planner = ShockwavePlanner(
        {"num_gpus": num_gpus, "time_per_iteration": 120.0,
         "future_rounds": 4, **config},
        backend="pdhg",
    )
    for i in range(num_jobs):
        planner.add_job(
            f"job{i}", make_profile([32] * 6, [200.0] * 6), 120.0, 1
        )
    return planner


class TestFingerprints:
    def test_matching_states_diff_empty(self):
        planner = make_planner()
        fp = planner_fingerprint(planner)
        assert diff_fingerprints(fp, planner_fingerprint(planner), 0) == {}

    def test_arrival_departure_drift_capacity(self):
        planner = make_planner()
        fp = planner_fingerprint(planner)
        planner.add_job(
            "late", make_profile([32] * 6, [200.0] * 6), 120.0, 1
        )
        diff = diff_fingerprints(fp, planner_fingerprint(planner), 0)
        assert any("arrived" in r for rs in diff.values() for r in rs)
        planner.remove_job("late")
        planner.remove_job("job0")
        diff = diff_fingerprints(fp, planner_fingerprint(planner), 0)
        assert any("departed" in r for rs in diff.values() for r in rs)
        planner = make_planner()
        planner.set_progress("job1", 2)
        diff = diff_fingerprints(fp, planner_fingerprint(planner), 0)
        assert any("progress" in r for rs in diff.values() for r in rs)
        # ...but inside the tolerance it is not churn.
        assert diff_fingerprints(fp, planner_fingerprint(planner), 2) == {}
        planner = make_planner()
        planner.set_capacity(2)
        diff = diff_fingerprints(fp, planner_fingerprint(planner), 0)
        assert any("capacity" in r for rs in diff.values() for r in rs)

    def test_completed_jobs_leave_the_fingerprint(self):
        planner = make_planner()
        planner.set_progress("job0", 6)  # finished: not live state
        fp = planner_fingerprint(planner)
        assert "job0" not in fp["progress"]


class TestReconcileStateMachine:
    def outcome(self, planner, **kw):
        return SpecOutcome(
            target_round=kw.pop("target_round", planner.round_index + 1),
            progress=kw.pop("progress", {}),
            throughputs=kw.pop("throughputs", []),
            completions=kw.pop("completions", []),
            capacity=kw.pop("capacity", planner.num_gpus),
        )

    def advance(self, planner):
        planner.current_round_schedule()
        planner.increment_round()

    def test_hit_installs_without_a_boundary_solve(self):
        planner = make_planner()
        self.advance(planner)
        spec = planner.speculate_next_round(self.outcome(planner))
        assert spec.ok
        solves_before = len(planner.solve_records)
        planner.increment_round()
        planner.recompute_flag = True  # make the boundary stale...
        planner.recompute_flag = False  # ...no: clean boundary, hit
        planner.current_round_schedule()
        assert planner.spec_stats["hit"] == 1
        # Cache was still valid at the target boundary, so the clone
        # did not solve and the live planner paid nothing either.
        assert len(planner.solve_records) == solves_before

    def test_round_skew_is_a_miss(self):
        planner = make_planner()
        self.advance(planner)
        planner.speculate_next_round(
            self.outcome(planner, target_round=planner.round_index + 1)
        )
        planner.increment_round()
        planner.increment_round()  # boundary overshoots the target
        planner.current_round_schedule()
        assert planner.spec_stats["miss"] == 1

    def test_join_timeout_is_a_miss(self):
        planner = make_planner(speculate_join_s=0.0)
        self.advance(planner)
        spec = planner.speculate_next_round(self.outcome(planner))
        spec.done.clear()  # simulate a still-running background solve
        planner._speculation = spec
        planner.increment_round()
        planner.current_round_schedule()
        assert planner.spec_stats["miss"] == 1

    def test_churn_on_cache_valid_boundary_discards(self):
        planner = make_planner()
        self.advance(planner)
        planner.speculate_next_round(self.outcome(planner))
        # Churn (arrival) against a boundary whose cache stays valid:
        # serial would NOT replan, so the speculation must be discarded
        # rather than repaired.
        planner.add_job(
            "late", make_profile([32] * 6, [200.0] * 6), 120.0, 1
        )
        planner.increment_round()
        solves_before = len(planner.solve_records)
        planner.current_round_schedule()
        assert planner.spec_stats["miss"] == 1
        assert len(planner.solve_records) == solves_before

    def test_churn_on_stale_boundary_repairs_through_pdhg(self):
        planner = make_planner()
        self.advance(planner)
        planner.speculate_next_round(self.outcome(planner))
        planner.add_job(
            "late", make_profile([32] * 6, [200.0] * 6), 120.0, 1
        )
        planner.set_recompute_flag()  # the boundary was going to solve
        planner.increment_round()
        planner.current_round_schedule()
        assert planner.spec_stats["repair"] == 1
        assert planner.solve_records[-1].get("repair") is True
        assert planner.solve_records[-1]["backend"] == "pdhg"

    def test_speculative_clone_shares_no_mutable_state(self):
        from shockwave_tpu.policies.speculation import clone_planner

        planner = make_planner()
        clone = clone_planner(planner)
        clone.record_round_throughput("job0", 1, 5.0, 32)
        clone.set_progress("job0", 3)
        clone.job_metadata["job0"].dirichlet[32] = 999.0
        assert planner.job_metadata["job0"].throughput_schedule == {}
        assert planner.job_metadata["job0"].completed_epochs == 0
        assert planner.job_metadata["job0"].dirichlet[32] != 999.0


class TestRecorderOverlay:
    def test_speculative_records_do_not_advance_accumulation(self, tmp_path):
        """A speculative record's predicted throughput tail must not
        shift the base the next LIVE record delta-encodes against."""
        from shockwave_tpu.obs.recorder import (
            FlightRecorder,
            decode,
            iter_records,
        )

        recorder = FlightRecorder()
        recorder.configure(str(tmp_path / "log.jsonl"))
        planner = make_planner(num_jobs=1)
        planner.record_round_throughput("job0", 1, 4.0, 32)
        state = planner.state_dict()
        recorder.record_plan(
            planner_state=state, plan={0: ["job0"]}, backend="pdhg",
            objective=0.0, tags={"speculative": True},
        )
        recorder.record_plan(
            planner_state=state, plan={0: ["job0"]}, backend="pdhg",
            objective=0.0,
        )
        recorder.close()
        plans = [
            r
            for r in iter_records(str(tmp_path / "log.jsonl"))
            if r.get("event") == "plan"
        ]
        assert plans[0].get("speculative") is True
        md_spec = decode(plans[0]["planner_state"])["job_metadata"]["job0"]
        md_live = decode(plans[1]["planner_state"])["job_metadata"]["job0"]
        # Both records carry the tail from base 0 — the speculative
        # overlay did not consume it.
        assert md_spec["tput_base"] == 0
        assert md_live["tput_base"] == 0
        assert list(md_live["tput_rounds"]) == [1]


# ----------------------------------------------------------------------
# Dirichlet change-point reweight (satellite): calibration assertion on
# the batch-size-switching fixture.
# ----------------------------------------------------------------------
class TestDirichletChangepoint:
    def bs_switch_fixture(self):
        """Profile: 30 small-bs epochs then 30 big-bs; reality: the gns
        switch lands at epoch 10. Durations 100 s / 50 s per regime."""
        from shockwave_tpu.predictor.metadata import JobMetadata

        md = JobMetadata(
            make_profile([32] * 30 + [64] * 30, [100.0] * 30 + [50.0] * 30),
            round_duration=60.0,
        )
        # Measured schedule: rounds 1..3 at bs 32, rounds 4..6 at bs 64
        # — the switch is OBSERVED far earlier than the profile's
        # epoch-30 pattern claims.
        for r in (1, 2, 3):
            md.record_round_throughput(r, 5.0, 32)
        for r in (4, 5, 6):
            md.record_round_throughput(r, 9.0, 64)
        return md

    def test_static_job_posterior_unchanged(self):
        from shockwave_tpu.predictor.metadata import JobMetadata

        md = JobMetadata(
            make_profile([32, 32, 64, 64], [100, 100, 50, 50]),
            round_duration=60,
        )
        md.complete(1)
        baseline = md.remaining_runtime()
        # Measured rounds WITHOUT a switch: bit-identical posterior.
        md.record_round_throughput(1, 5.0, 32)
        md.record_round_throughput(2, 5.0, 32)
        md2 = JobMetadata(
            make_profile([32, 32, 64, 64], [100, 100, 50, 50]),
            round_duration=60,
        )
        md2.complete(1)
        md2.record_round_throughput(1, 5.0, 32)
        md2.record_round_throughput(2, 5.0, 32)
        assert md.remaining_runtime() == md2.remaining_runtime()
        del baseline

    def test_measured_switch_reanchors_remaining_runtime(self):
        md = self.bs_switch_fixture()
        md.complete(20)
        # Ground truth: 40 remaining epochs, all in the observed big-bs
        # regime (the job switched at epoch 10 and gns never switches
        # back). recompute_epoch_durations rescales all durations by a
        # common factor, so compare against the rescaled regime price.
        durations = md.bs_epoch_durations()
        truth = (md.total_epochs - (md.completed_epochs + 1)) * durations[64]
        predicted = md.remaining_runtime()
        ape = abs(predicted - truth) / truth
        # Calibration assertion: the change-point reweight holds the
        # fixture's absolute percentage error under 10% — the unweighted
        # posterior (below) mis-prices the old regime's phantom epochs.
        assert ape < 0.10, f"APE {ape:.3f} (pred {predicted}, true {truth})"

        import shockwave_tpu.predictor.metadata as meta

        old = meta.CHANGEPOINT_RETAIN
        meta.CHANGEPOINT_RETAIN = 1.0  # disable the reweight
        try:
            md._changepoint_key = None  # drop the memo
            unweighted = md.remaining_runtime()
        finally:
            meta.CHANGEPOINT_RETAIN = old
        ape_unweighted = abs(unweighted - truth) / truth
        assert ape < ape_unweighted

    def test_changepoint_is_pure_function_of_schedule(self):
        """Replay/checkpoint safety: a planner restored from state_dict
        re-derives the identical change-point posterior."""
        md = self.bs_switch_fixture()
        md.complete(20)
        predicted = md.remaining_runtime()
        from shockwave_tpu.predictor.metadata import JobMetadata

        restored = JobMetadata.from_state(md.state_dict())
        assert restored.remaining_runtime() == predicted
