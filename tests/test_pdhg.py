"""Restarted-PDHG solver backend (shockwave_tpu/solver/eg_pdhg.py).

Coverage contract (ISSUE 8): convergence on small analytic EG instances
and objective parity with the level backend, restart-triggering
behavior, solution warm-start round trip (both the s0 path and the
serialized-executable compile cache), ladder-rung fallback under an
injected solver_timeout, and sharded-vs-single-device agreement on the
8-virtual-device mesh.
"""

import numpy as np
import pytest

import bench
from shockwave_tpu.runtime import faults
from shockwave_tpu.solver import warm_start
from shockwave_tpu.solver.eg_jax import num_slots_for, solve_eg_level
from shockwave_tpu.solver.eg_pdhg import (
    DEFAULT_INNER_ITERS,
    DEFAULT_MAX_CYCLES,
    polish_relaxed,
    solve_eg_pdhg,
    solve_pdhg_relaxed,
    solve_pdhg_relaxed_sharded,
)
from shockwave_tpu.solver.eg_problem import EGProblem
from shockwave_tpu.solver.rounding import round_counts


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


def _counts_objective(problem, counts):
    R = problem.future_rounds
    Y = (np.arange(R)[None, :] < np.asarray(counts)[:, None]).astype(float)
    return problem.objective_value(Y)


# -- convergence & parity ----------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 5])
def test_matches_level_backend(seed):
    """The full pdhg backend (device solve + rounding + polish +
    placement) lands within 0.1% of the production level backend on the
    mid-scale bench shape — the ISSUE 8 parity bar, at test scale."""
    p = bench.make_problem(
        num_jobs=100, future_rounds=20, num_gpus=64, seed=seed
    )
    Y = solve_eg_pdhg(p)
    p.audit_schedule(Y)
    o_pdhg = p.objective_value(Y)
    o_level = p.objective_value(solve_eg_level(p))
    assert o_pdhg >= o_level - 1e-3 * abs(o_level)


def test_analytic_single_job_completes():
    """One job, ample budget: the solve must grant at least the rounds
    that finish the job (welfare saturated, zero lateness) and report a
    near-zero objective (log(1) welfare, no makespan)."""
    p = EGProblem(
        priorities=np.array([2.0]),
        completed_epochs=np.array([0.0]),
        total_epochs=np.array([4.0]),
        epoch_duration=np.array([60.0]),
        remaining_runtime=np.array([240.0]),
        nworkers=np.array([1.0]),
        num_gpus=4,
        round_duration=60.0,
        future_rounds=10,
        regularizer=10.0,
        log_bases=np.array([0.0, 0.2, 0.4, 0.6, 0.8, 1.0]),
    )
    s, obj, info = solve_pdhg_relaxed(p)
    assert info["converged"]
    assert s[0] >= 4.0 - 1e-3
    assert abs(obj) < 1e-3


def test_analytic_symmetric_jobs_split_evenly():
    """Identical jobs under half-demand budget: the unique optimum of
    the strictly concave welfare is the even split s_j = budget / J."""
    J = 8
    p = EGProblem(
        priorities=np.full(J, 3.0),
        completed_epochs=np.zeros(J),
        total_epochs=np.full(J, 10.0),
        epoch_duration=np.full(J, 100.0),
        remaining_runtime=np.full(J, 1000.0),
        nworkers=np.ones(J),
        num_gpus=4,
        round_duration=100.0,
        future_rounds=10,
        regularizer=1e-3,
        log_bases=np.array([0.0, 0.2, 0.4, 0.6, 0.8, 1.0]),
    )
    s, _, _ = solve_pdhg_relaxed(p)
    assert np.all(np.abs(s - 5.0) < 0.35), s
    assert float(np.sum(s)) <= 40.0 + 1e-3


def test_switch_bonus_keeps_incumbent():
    """A low-priority incumbent with a large relaunch overhead must keep
    a round that the overhead-blind objective would hand to the
    high-priority jobs (the conformance term, observed end to end)."""
    J = 4
    base = dict(
        priorities=np.array([0.01, 10.0, 10.0, 10.0]),
        completed_epochs=np.zeros(J),
        total_epochs=np.full(J, 10.0),
        epoch_duration=np.full(J, 100.0),
        remaining_runtime=np.full(J, 1000.0),
        nworkers=np.ones(J),
        num_gpus=1,
        round_duration=100.0,
        future_rounds=4,
        regularizer=1e-3,
        log_bases=np.array([0.0, 0.2, 0.4, 0.6, 0.8, 1.0]),
    )
    blind = EGProblem(**base)
    s_blind, _, _ = solve_pdhg_relaxed(blind)
    c_blind = round_counts(s_blind, blind.nworkers, 1, 4)
    assert c_blind[0] == 0, c_blind

    # bonus = regularizer * switch_cost = 100: dwarfs the ~0.5/round
    # welfare marginals of the other three jobs.
    aware = EGProblem(
        **base,
        switch_cost=np.array([1e5, 0.0, 0.0, 0.0]),
        incumbent=np.array([1.0, 0.0, 0.0, 0.0]),
    )
    s_aware, _, _ = solve_pdhg_relaxed(aware)
    c_aware = round_counts(s_aware, aware.nworkers, 1, 4)
    assert c_aware[0] >= 1, c_aware


# -- restarts & warm starts --------------------------------------------


def test_restarts_trigger_and_preserve_quality():
    """With the objective-stall stop disabled the adaptive machinery
    engages: restart-to-average fires, and the long run's rounded
    objective matches the default adaptive stop (the early stop isn't
    trading quality for wall clock)."""
    p = bench.make_problem(
        num_jobs=1000, future_rounds=50, num_gpus=256, seed=0
    )
    s_default, _, info_default = solve_pdhg_relaxed(p)
    s_long, _, info_long = solve_pdhg_relaxed(
        p, stall_rel=-1.0, tol=1e-6, max_cycles=40
    )
    assert info_long["restarts"] >= 1
    assert info_long["cycles"] > info_default["cycles"]
    o_default = _counts_objective(
        p, round_counts(s_default, p.nworkers, p.num_gpus, p.future_rounds)
    )
    o_long = _counts_objective(
        p, round_counts(s_long, p.nworkers, p.num_gpus, p.future_rounds)
    )
    assert o_default >= o_long - 1e-3 * abs(o_long)


def test_solution_warm_start_roundtrip():
    """Re-solving from the returned iterate terminates at least as fast
    and never loses objective (best tracking starts at the projected
    warm start); a garbage warm start is clipped into the box and still
    converges to the same quality."""
    p = bench.make_problem(
        num_jobs=100, future_rounds=20, num_gpus=64, seed=2
    )
    s1, obj1, info1 = solve_pdhg_relaxed(p)
    s2, obj2, info2 = solve_pdhg_relaxed(p, s0=s1)
    assert obj2 >= obj1 - 1e-5 * (1.0 + abs(obj1))
    assert info2["cycles"] <= info1["cycles"] + 1
    s3, obj3, _ = solve_pdhg_relaxed(p, s0=np.full(p.num_jobs, -7.0))
    assert obj3 >= obj1 - 1e-3 * (1.0 + abs(obj1))


def test_polish_never_hurts():
    """polish_relaxed is the PGD parity-gap closer: from ANY feasible
    iterate it returns a point no worse in the true relaxed objective."""
    p = bench.make_problem(
        num_jobs=100, future_rounds=20, num_gpus=64, seed=3
    )
    rng = np.random.default_rng(0)
    rough = rng.uniform(0.0, p.future_rounds, p.num_jobs)
    _, obj_ref, _ = solve_pdhg_relaxed(p)
    polished = polish_relaxed(p, rough)
    _, obj_at_polished, _ = solve_pdhg_relaxed(p, s0=polished, max_cycles=0)
    _, obj_at_rough, _ = solve_pdhg_relaxed(p, s0=rough, max_cycles=0)
    assert obj_at_polished >= obj_at_rough - 1e-6 * (1 + abs(obj_at_rough))
    assert obj_at_polished >= obj_ref - 1e-2 * (1 + abs(obj_ref))


def test_warm_executable_roundtrip(tmp_path, monkeypatch):
    """Compile warm start (warm_start.warm_pdhg): a serialized
    executable for the pdhg entry loads under its own cache key and the
    fast path produces bit-identical results to the jitted path."""
    monkeypatch.setenv("SHOCKWAVE_SOLVER_CACHE_DIR", str(tmp_path))
    saved = dict(warm_start._LOADED)
    warm_start._LOADED.clear()
    try:
        p = bench.make_problem(
            num_jobs=40, future_rounds=8, num_gpus=16, seed=0
        )
        slots = num_slots_for(p.num_jobs)
        tag = f"c{DEFAULT_MAX_CYCLES}i{DEFAULT_INNER_ITERS}"
        assert not warm_start.available(
            slots, 0, 0, True, num_bases=0, entry="solve_pdhg",
            shape_tag=tag,
        )
        s_ref, obj_ref, _ = solve_pdhg_relaxed(p)
        warm_start.warm_pdhg(slots)
        assert warm_start.available(
            slots, 0, 0, True, num_bases=0, entry="solve_pdhg",
            shape_tag=tag,
        )
        assert (
            warm_start.load(
                slots, 0, 0, True, num_bases=0, entry="solve_pdhg",
                shape_tag=tag,
            )
            is not None
        )
        s, obj, _ = solve_pdhg_relaxed(p)
        np.testing.assert_array_equal(s, s_ref)
        assert obj == obj_ref
        key = warm_start.cache_key(
            slots, 0, 0, True, num_bases=0, entry="solve_pdhg",
            shape_tag=tag,
        )
        assert warm_start._LOADED.get(key) is not None, (
            "pdhg executable was invalidated at call time; the solve "
            "silently fell back to the jitted path"
        )
    finally:
        warm_start._LOADED.clear()
        warm_start._LOADED.update(saved)


def test_cache_key_separates_entries():
    level = warm_start.cache_key(1024, 50, 64, True)
    pdhg = warm_start.cache_key(
        1024, 50, 64, True, entry="solve_pdhg"
    )
    tagged = warm_start.cache_key(
        1024, 50, 64, True, entry="solve_pdhg", shape_tag="c96i40"
    )
    assert len({level, pdhg, tagged}) == 3


# -- planner integration ------------------------------------------------


PROFILE = {
    "num_epochs": 4,
    "num_samples_per_epoch": 64,
    "scale_factor": 1,
    "bs_every_epoch": [32] * 4,
    "duration_every_epoch": [120.0] * 4,
}


def _tiny_planner(backend, plan_deadline_s=None):
    from shockwave_tpu.policies.shockwave import ShockwavePlanner

    config = {
        "num_gpus": 2,
        "time_per_iteration": 60.0,
        "future_rounds": 4,
        "lambda": 2.0,
        "k": 1e-3,
    }
    if plan_deadline_s is not None:
        config["plan_deadline_s"] = plan_deadline_s
    planner = ShockwavePlanner(config, backend=backend)
    for j in range(3):
        planner.add_job(j, dict(PROFILE), 60.0, 1)
    return planner


def test_pdhg_backend_plans_and_warm_starts():
    planner = _tiny_planner("pdhg")
    schedule = planner.current_round_schedule()
    assert schedule
    assert planner.solve_records[-1]["backend"] == "pdhg"
    # The cached plan seeds the next replan's solution warm start.
    s0 = planner._solution_warm_start()
    assert s0 is not None and s0.sum() > 0
    planner.set_recompute_flag()
    assert planner.current_round_schedule()
    assert planner.solve_records[-1]["backend"] == "pdhg"


def test_replay_reproduces_warm_started_plans(tmp_path):
    """Flight-recorder exactness with the pdhg backend: the solution
    warm start is derived from the pre-replan plan cache, which the
    recorder slims out of its snapshots — the recorded
    ``pdhg_warm_start`` vector must carry it, or replayed replans
    re-enter the solve from the default start and diverge (the bug
    this test pins)."""
    from shockwave_tpu import obs
    from shockwave_tpu.obs.recorder import replay_log

    log_path = str(tmp_path / "decisions.jsonl")
    obs.reset()
    obs.configure_recorder(log_path)
    try:
        planner = _tiny_planner("pdhg")
        planner.current_round_schedule()
        # Second replan: warm-started from the first plan's cache.
        planner.increment_round()
        planner.set_recompute_flag()
        planner.current_round_schedule()
        obs.get_recorder().close()
        results = replay_log(log_path)
        assert len(results) == 2
        diverged = [r for r in results if r["diff"]]
        assert not diverged, diverged
    finally:
        obs.reset()


def test_ladder_falls_back_to_pdhg_rung():
    """Injected solver_timeout on the primary rung: the new pdhg rung
    (between primary and relaxed) absorbs the fault, and the record
    carries the full ladder attribution."""
    plan = faults.FaultPlan(
        seed=0, events=[faults.FaultEvent(0, "solver_timeout", round=0)]
    )
    injector = faults.configure(plan)
    planner = _tiny_planner("tpu", plan_deadline_s=10.0)
    schedule = planner.current_round_schedule()
    assert schedule, "ladder fallback produced no plan"
    record = planner.solve_records[-1]
    assert record["ok"]
    assert record["degraded"] is True
    assert record["fallback_from"] == "tpu"
    assert record["ladder"][0]["outcome"] == "timeout_injected"
    assert record["ladder"][1] == {"backend": "pdhg", "outcome": "ok"}
    assert record["backend"] == "pdhg"
    assert injector.summary()["unrecovered"] == []


def test_broken_pdhg_cannot_take_out_relaxed_rung(monkeypatch):
    """Fallback isolation: with the PDHG kernel itself raising, the
    ladder must still recover through the relaxed rung — which skips
    its PDHG polish when running as a fallback, precisely so the
    failing kernel cannot claim two of the three recovery rungs."""
    import shockwave_tpu.solver.eg_pdhg as eg_pdhg

    def boom(*args, **kwargs):
        raise RuntimeError("pdhg kernel down")

    monkeypatch.setattr(eg_pdhg, "solve_pdhg_relaxed", boom)
    monkeypatch.setattr(eg_pdhg, "solve_eg_pdhg", boom)
    monkeypatch.setattr(eg_pdhg, "polish_relaxed", boom)
    planner = _tiny_planner("pdhg", plan_deadline_s=10.0)
    schedule = planner.current_round_schedule()
    assert schedule, "ladder produced no plan with the pdhg kernel down"
    record = planner.solve_records[-1]
    assert record["ok"]
    assert record["degraded"] is True
    assert record["fallback_from"] == "pdhg"
    # "relaxed", not "native": the relaxed rung succeeded WITHOUT
    # touching the broken polish (a polish call would have raised).
    assert record["backend"] == "relaxed"


# -- sharded agreement --------------------------------------------------


def test_sharded_matches_single_device():
    """Same problem through the single-device and 8-virtual-device
    shard_map paths: identical arithmetic up to float accumulation
    order, so the iterates agree tightly and the rounded schedules
    agree in objective."""
    import jax

    assert len(jax.devices()) == 8
    p = bench.make_problem(
        num_jobs=100, future_rounds=20, num_gpus=64, seed=0
    )
    s1, obj1, info1 = solve_pdhg_relaxed(p)
    s8, obj8, info8 = solve_pdhg_relaxed_sharded(p)
    assert abs(obj8 - obj1) <= 1e-3 * (1.0 + abs(obj1)), (obj1, obj8)
    np.testing.assert_allclose(s8, s1, rtol=5e-3, atol=5e-3)
    o1 = _counts_objective(
        p, round_counts(s1, p.nworkers, p.num_gpus, p.future_rounds)
    )
    o8 = _counts_objective(
        p, round_counts(s8, p.nworkers, p.num_gpus, p.future_rounds)
    )
    assert abs(o8 - o1) <= 2e-3 * (1.0 + abs(o1)), (o1, o8)


def test_sharded_pad_not_divisible_by_mesh():
    """129 jobs pad to 256 slots (divisible by 8 only after rounding up
    from 129): the shard-padding arithmetic must not disturb results."""
    p = bench.make_problem(
        num_jobs=129, future_rounds=10, num_gpus=48, seed=4
    )
    s1, obj1, _ = solve_pdhg_relaxed(p)
    s8, obj8, _ = solve_pdhg_relaxed_sharded(p)
    assert s8.shape == (129,)
    assert abs(obj8 - obj1) <= 1e-3 * (1.0 + abs(obj1))
