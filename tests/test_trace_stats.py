"""Trace statistics tool: correct counts and distributions on the
committed standalone traces."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    spec = importlib.util.spec_from_file_location(
        "trace_stats",
        os.path.join(REPO, "scripts", "analysis", "trace_stats.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_stats_on_committed_trace():
    mod = _load()
    s = mod.stats(os.path.join(REPO, "traces", "small_12_dynamic.trace"))
    assert s["num_jobs"] == 12
    assert sum(s["scale_factors"].values()) == 12
    assert sum(s["modes"].values()) == 12
    assert sum(s["families"].values()) == 12
    assert s["duration_mean_s"] > 0
    assert s["total_gpu_hours"] > 0
    assert s["arrival_span_s"] > 0
    assert s["duration_p50_s"] <= s["duration_p90_s"]
