"""Mixture-of-experts layer: grouped dispatch must match the dense
one-hot reference exactly when nothing overflows, drop overflow tokens
to the residual when capacity binds, and the Switch-style auxiliary
loss must actually rebalance a collapsed router."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shockwave_tpu.models.transformer import (
    MoEMlp,
    TransformerConfig,
    TransformerLM,
    lm_loss,
    moe_aux_loss,
)


def _cfg(**kw):
    base = dict(
        vocab_size=64, d_model=16, num_heads=2, num_layers=1, d_ff=32,
        max_len=32, num_experts=4,
    )
    base.update(kw)
    return TransformerConfig(**base)


def _moe_and_input(cfg, seed=0, batch=2, seq=32, positive=False):
    rng = np.random.default_rng(seed)
    raw = rng.normal(size=(batch, seq, cfg.d_model))
    if positive:
        # All-positive features make a one-hot router kernel column a
        # deterministic collapse (its gate is strictly the max).
        raw = np.abs(raw) + 0.1
    x = jnp.asarray(raw, jnp.float32)
    moe = MoEMlp(cfg)
    variables = moe.init(jax.random.PRNGKey(seed), x)
    return moe, variables, x


def test_grouped_matches_dense_dispatch_when_capacity_is_ample():
    cfg_g = _cfg(moe_dispatch="grouped", moe_capacity_factor=4.0)
    cfg_d = _cfg(moe_dispatch="dense")
    moe_g, variables, x = _moe_and_input(cfg_g)
    y_g = moe_g.apply(variables, x)
    y_d = MoEMlp(cfg_d).apply(variables, x)
    np.testing.assert_allclose(
        np.asarray(y_g), np.asarray(y_d), rtol=1e-6, atol=1e-6
    )


def test_grouped_dispatch_is_differentiable():
    cfg = _cfg(moe_dispatch="grouped", moe_capacity_factor=2.0)
    moe, variables, x = _moe_and_input(cfg)

    def loss(v):
        y, mutated = moe.apply(v, x, mutable=["losses"])
        return jnp.sum(y**2) + moe_aux_loss(mutated)

    g = jax.grad(loss)(variables)
    for leaf in jax.tree_util.tree_leaves(g):
        assert np.all(np.isfinite(np.asarray(leaf)))
    # The router learns through the gate scale AND the aux loss.
    assert np.any(
        np.asarray(g["params"]["router"]["kernel"]) != 0.0
    )


def test_overflow_tokens_drop_to_zero_output():
    """With every token routed to one expert and capacity < tokens, the
    overflow tokens' MLP contribution must be exactly zero (residual
    passes through in the Block), and in-capacity tokens must match the
    dense dispatch."""
    cfg = _cfg(moe_dispatch="grouped", moe_capacity_factor=0.25)
    moe, variables, x = _moe_and_input(cfg, positive=True)
    # Collapse the router onto expert 0.
    kernel = np.zeros((cfg.d_model, cfg.num_experts), np.float32)
    kernel[:, 0] = 1.0
    variables = {
        "params": {**variables["params"], "router": {"kernel": jnp.asarray(kernel)}}
    }
    y = np.asarray(moe.apply(variables, x))
    B, S, d = x.shape
    N = B * S
    # capacity = ceil(0.25 * N / E) rounded up to a multiple of 8
    C = int(np.ceil(0.25 * N / cfg.num_experts))
    C = -(-C // 8) * 8
    flat = y.reshape(N, d)
    nonzero = np.any(flat != 0.0, axis=1)
    assert nonzero[:C].all(), "in-capacity tokens must be computed"
    assert not nonzero[C:].any(), "overflow tokens must drop to zero"


def test_router_aux_loss_rebalances_skewed_batch():
    """Gradient-descending the auxiliary loss alone must spread a
    skewed router back across experts on a diverse token batch: the
    max per-expert dispatch fraction decreases to ~uniform and the aux
    value reaches its uniform minimum of 1. (The batch must be
    DIVERSE: identically-signed tokens all flip together, so top-1
    balance cannot emerge from any router.)"""
    cfg = _cfg(moe_dispatch="grouped", moe_capacity_factor=4.0)
    moe, variables, x = _moe_and_input(cfg, seed=3)
    rng = np.random.default_rng(3)
    kernel = np.asarray(
        rng.normal(size=(cfg.d_model, cfg.num_experts)) * 0.1, np.float32
    )
    kernel[0, 0] += 1.5  # skew: expert 0 over-favored
    params = {
        **variables["params"], "router": {"kernel": jnp.asarray(kernel)}
    }

    def aux(p):
        _, mutated = moe.apply({"params": p}, x, mutable=["losses"])
        return moe_aux_loss(mutated)

    def max_frac(p):
        top = jnp.argmax(
            x.reshape(-1, cfg.d_model) @ p["router"]["kernel"], axis=-1
        )
        counts = jnp.bincount(top, length=cfg.num_experts)
        return float(jnp.max(counts) / top.shape[0])

    aux0, frac0 = float(aux(params)), max_frac(params)
    assert frac0 > 0.4, frac0  # 0.25 is uniform for 4 experts
    grad_fn = jax.jit(jax.grad(aux))
    for _ in range(100):
        g = grad_fn(params)
        params = jax.tree_util.tree_map(
            lambda p, gp: p - 0.5 * gp, params, g
        )
    aux1, frac1 = float(aux(params)), max_frac(params)
    assert frac1 < frac0, (frac0, frac1)
    assert aux1 < aux0, (aux0, aux1)
    # Balanced, not merely less skewed (uniform: frac 0.25, aux 1.0).
    assert frac1 <= 0.3, frac1
    assert aux1 <= 1.01, aux1


def test_lm_loss_includes_aux_term():
    cfg_on = _cfg(moe_aux_weight=1e-1)
    cfg_off = _cfg(moe_aux_weight=0.0)
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, 64, (2, 33)), jnp.int32)
    m_on = TransformerLM(cfg_on)
    m_off = TransformerLM(cfg_off)
    variables = jax.jit(m_on.init)(jax.random.PRNGKey(0), tokens[:, :-1])
    assert set(variables) == {"params"}, (
        "sown aux losses must not leak into init variables"
    )
    loss_on = float(lm_loss(m_on, variables, tokens))
    loss_off = float(lm_loss(m_off, variables, tokens))
    assert loss_on > loss_off, (loss_on, loss_off)
    # The gap is exactly weight * mean aux (aux >= 1/E... > 0).
    assert loss_on - loss_off > 1e-3


def test_invalid_moe_config_rejected():
    moe, variables, x = _moe_and_input(_cfg())
    bad = MoEMlp(_cfg(moe_dispatch="sorted"))
    with pytest.raises(ValueError, match="moe_dispatch"):
        bad.init(jax.random.PRNGKey(0), x)
    bad = MoEMlp(_cfg(moe_capacity_factor=0.0))
    with pytest.raises(ValueError, match="moe_capacity_factor"):
        bad.init(jax.random.PRNGKey(0), x)
