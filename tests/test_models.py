"""Workload-family tests: every family trains a few steps with finite,
decreasing loss on CPU, and checkpoints roundtrip."""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Whole module drives training subprocesses / full simulations.
pytestmark = pytest.mark.slow

from shockwave_tpu.models.train import build_family, main as train_main
from shockwave_tpu.parallel.mesh import make_mesh

FAMILIES = [
    "ResNet-18",
    "Transformer",
    "LM",
    "Recommendation",
    "A3C",
    "CycleGAN",
]


def tiny_args(model, **overrides):
    import argparse

    defaults = dict(
        model=model,
        batch_size=4,
        num_steps=3,
        checkpoint_dir=None,
        enable_shockwave_iterator=False,
        learning_rate=1e-3,
        seed=0,
        vocab_size=64,
        d_model=32,
        num_heads=2,
        num_layers=1,
        seq_len=16,
        attention="dense",
        num_experts=0,
        model_parallel=1,
        seq_parallel=1,
        distributed_addr=None,
        num_workers=1,
        worker_rank=0,
    )
    defaults.update(overrides)
    return argparse.Namespace(**defaults)


@pytest.mark.parametrize("family", FAMILIES)
def test_family_train_steps_reduce_loss(family):
    mesh = make_mesh((1, 1, 1), devices=jax.devices()[:1])
    args = tiny_args(family)
    variables, step_fn, opt_state, batch_fn = build_family(family, args, mesh)
    rng = np.random.default_rng(0)
    step = jax.jit(step_fn)
    losses = []
    batch = batch_fn(rng)  # same batch: loss must drop when overfitting it
    for _ in range(8):
        variables, opt_state, loss = step(variables, opt_state, batch)
        losses.append(float(loss))
    assert np.all(np.isfinite(losses))
    if family not in ("A3C", "CycleGAN"):
        # Two families are NOT monotone-descent objectives, and
        # asserting descent on them was a category error (pre-existing
        # flaky debt since PR 3, burned down here):
        #   * A3C — policy gradient + entropy bonus, a surrogate whose
        #     scalar moves with the sampled advantage;
        #   * CycleGAN — the recorded scalar is gen_loss + disc_loss of
        #     an adversarial minimax game: every generator improvement
        #     RAISES the discriminator's loss on the better fakes (and
        #     vice versa), so the sum oscillates by construction even
        #     when both players are training correctly.
        # Finiteness is the contract for both; the dense families keep
        # the strict descent gate.
        assert losses[-1] < losses[0]


def test_transformer_ring_attention_tp_mesh():
    # dp=2 x tp=2 x sp=2 mesh with ring attention + MoE experts.
    mesh = make_mesh((2, 2, 2))
    args = tiny_args(
        "Transformer", attention="ring", num_experts=2, seq_len=16
    )
    variables, step_fn, opt_state, batch_fn = build_family(
        "Transformer", args, mesh
    )
    rng = np.random.default_rng(0)
    with mesh:
        step = jax.jit(step_fn)
        batch = batch_fn(rng)
        variables, opt_state, loss = step(variables, opt_state, batch)
    assert np.isfinite(float(loss))


def test_train_cli_end_to_end(tmp_path):
    # The exact process shape the dispatcher launches.
    result = subprocess.run(
        [
            sys.executable,
            "-m",
            "shockwave_tpu.models.train",
            "--model",
            "Recommendation",
            "--batch_size",
            "8",
            "-n",
            "3",
            "--checkpoint_dir",
            str(tmp_path),
        ],
        capture_output=True,
        text=True,
        timeout=180,
        env={
            **__import__("os").environ,
            "JAX_PLATFORMS": "cpu",
        },
    )
    assert result.returncode == 0, result.stderr
    assert "steps=3" in result.stdout
    assert (tmp_path / "train_state.msgpack").exists()


def test_checkpoint_roundtrip(tmp_path):
    mesh = make_mesh((1, 1, 1), devices=jax.devices()[:1])
    args = tiny_args("LM", checkpoint_dir=str(tmp_path))
    variables, step_fn, opt_state, batch_fn = build_family("LM", args, mesh)
    from flax import serialization

    blob = serialization.to_bytes((variables, opt_state))
    variables2, opt_state2 = serialization.from_bytes(
        (variables, opt_state), blob
    )
    chex = pytest.importorskip("chex")
    chex.assert_trees_all_close(variables, variables2)


def test_legacy_optax_checkpoint_migrates(tmp_path):
    """A checkpoint written when the optimizer was optax.adamw (state
    keys count/mu/nu inside a 3-chain) must still resume after the
    fused-AdamW switch: the CLI's restore falls back to the legacy
    template and repacks it into FusedAdamWState instead of failing
    every relaunch on a template mismatch."""
    import optax
    from flax import serialization

    from shockwave_tpu.ops.fused_adamw import FusedAdamW

    cmd = [
        sys.executable, "-m", "shockwave_tpu.models.train",
        "--model", "Recommendation", "--batch_size", "8", "-n", "2",
        "--checkpoint_dir", str(tmp_path),
    ]
    env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
    out1 = subprocess.run(
        cmd, capture_output=True, text=True, timeout=180, env=env
    )
    assert out1.returncode == 0, out1.stderr

    # Rewrite the checkpoint in the LEGACY optax format.
    ckpt = tmp_path / "train_state.msgpack"
    mesh = make_mesh((1, 1, 1), devices=jax.devices()[:1])
    args = tiny_args(
        "Recommendation", batch_size=8, checkpoint_dir=str(tmp_path)
    )
    variables, _, _, _ = build_family("Recommendation", args, mesh)
    fused_template = FusedAdamW(args.learning_rate).init(variables)
    saved_vars, saved_state = serialization.from_bytes(
        (variables, fused_template), ckpt.read_bytes()
    )
    legacy = optax.adamw(args.learning_rate).init(saved_vars)
    legacy = (
        legacy[0]._replace(
            count=saved_state.count, mu=saved_state.m, nu=saved_state.v
        ),
    ) + tuple(legacy[1:])
    ckpt.write_bytes(serialization.to_bytes((saved_vars, legacy)))

    # Resume from the legacy-format checkpoint: must migrate, not crash.
    out2 = subprocess.run(
        cmd, capture_output=True, text=True, timeout=180, env=env
    )
    assert out2.returncode == 0, out2.stderr
    assert "steps=2" in out2.stdout


@pytest.mark.parametrize("attention", ["dense", "flash", "ulysses"])
def test_transformer_bfloat16_mixed_precision(attention):
    """bfloat16 activations (float32 params / softmax / layernorm) must
    produce logits close to the float32 model and train with finite
    loss on every attention path."""
    from shockwave_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )

    if attention == "ulysses":
        mesh = make_mesh((1, 1, 2), devices=jax.devices()[:2])
    else:
        mesh = make_mesh((1, 1, 1), devices=jax.devices()[:1])
    # seq_len=128 puts the flash path onto the real (interpret-mode)
    # kernel rather than its dense fallback.
    seq_len = 128 if attention in ("flash", "ulysses") else 32
    kwargs = dict(
        vocab_size=64,
        d_model=32,
        num_heads=2,
        num_layers=1,
        max_len=seq_len,
        attention=attention,
    )
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, seq_len)), jnp.int32
    )
    with mesh:
        logits = {}
        for dtype in ("float32", "bfloat16"):
            model = TransformerLM(
                TransformerConfig(dtype=dtype, **kwargs), mesh=mesh
            )
            variables = model.init(jax.random.PRNGKey(0), tokens)
            out = model.apply(variables, tokens)
            assert out.dtype == jnp.float32  # logits always f32
            logits[dtype] = np.asarray(out)
    # bfloat16 has ~3 decimal digits; logits are O(1) here.
    np.testing.assert_allclose(
        logits["bfloat16"], logits["float32"], atol=0.05, rtol=0.05
    )


def test_transformer_bfloat16_trains():
    mesh = make_mesh((1, 1, 1), devices=jax.devices()[:1])
    args = tiny_args("Transformer", dtype="bfloat16")
    variables, step_fn, opt_state, batch_fn = build_family(
        "Transformer", args, mesh
    )
    rng = np.random.default_rng(0)
    step = jax.jit(step_fn)
    batch = batch_fn(rng)
    losses = []
    for _ in range(8):
        variables, opt_state, loss = step(variables, opt_state, batch)
        losses.append(float(loss))
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_remat_matches_baseline_loss_and_grads():
    """jax.checkpoint'd blocks must be numerically identical to the
    baseline — remat changes memory, never math."""
    from shockwave_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        lm_loss,
    )

    mesh = make_mesh((1, 1, 1), devices=jax.devices()[:1])
    kwargs = dict(
        vocab_size=64, d_model=32, num_heads=2, num_layers=2,
        d_ff=64, max_len=32,
    )
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (2, 33)), jnp.int32
    )
    models = {
        flag: TransformerLM(TransformerConfig(remat=flag, **kwargs), mesh=mesh)
        for flag in (False, True)
    }
    params = models[False].init(jax.random.PRNGKey(0), tokens[:, :-1])
    out = {}
    for flag, model in models.items():
        loss, grads = jax.value_and_grad(
            lambda p: lm_loss(model, p, tokens)
        )(params)
        out[flag] = (float(loss), grads)
    assert out[False][0] == pytest.approx(out[True][0], rel=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(out[False][1]),
        jax.tree_util.tree_leaves(out[True][1]),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_orbax_checkpoint_roundtrip(tmp_path):
    """--ckpt_backend orbax must save on exit and restore on relaunch,
    continuing the loss trajectory like the msgpack backend."""
    pytest.importorskip("orbax.checkpoint")
    import os
    import subprocess

    cmd = [
        sys.executable, "-m", "shockwave_tpu.models.train",
        "--model", "Recommendation", "--batch_size", "64", "-n", "3",
        "--checkpoint_dir", str(tmp_path), "--ckpt_backend", "orbax",
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out1 = subprocess.run(cmd, capture_output=True, text=True, env=env)
    assert out1.returncode == 0, out1.stderr[-2000:]
    assert (tmp_path / "orbax_state").exists()
    out2 = subprocess.run(cmd, capture_output=True, text=True, env=env)
    assert out2.returncode == 0, out2.stderr[-2000:]

    def loss_of(out):
        import re

        return float(re.search(r"loss=([\d.]+)", out.stdout).group(1))

    # Training continued from the restored state: loss kept dropping.
    assert loss_of(out2) < loss_of(out1)


def test_legacy_optax_orbax_checkpoint_migrates(tmp_path):
    """The orbax flavor of the legacy migration: an orbax checkpoint
    whose optimizer state is in the optax.adamw layout must restore
    through the fallback template and repack into FusedAdamWState."""
    pytest.importorskip("orbax.checkpoint")
    import os
    import shutil
    import subprocess

    import optax
    import orbax.checkpoint as ocp

    from shockwave_tpu.ops.fused_adamw import FusedAdamW

    cmd = [
        sys.executable, "-m", "shockwave_tpu.models.train",
        "--model", "Recommendation", "--batch_size", "8", "-n", "2",
        "--checkpoint_dir", str(tmp_path), "--ckpt_backend", "orbax",
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    out1 = subprocess.run(
        cmd, capture_output=True, text=True, timeout=180, env=env
    )
    assert out1.returncode == 0, out1.stderr[-2000:]

    # Rewrite the orbax tree in the LEGACY optax layout.
    orbax_dir = tmp_path / "orbax_state"
    mesh = make_mesh((1, 1, 1), devices=jax.devices()[:1])
    args = tiny_args(
        "Recommendation", batch_size=8, checkpoint_dir=str(tmp_path)
    )
    variables, _, _, _ = build_family("Recommendation", args, mesh)
    fused_template = FusedAdamW(args.learning_rate).init(variables)
    checkpointer = ocp.StandardCheckpointer()
    restored = checkpointer.restore(
        str(orbax_dir), {"variables": variables, "opt": fused_template}
    )
    legacy = optax.adamw(args.learning_rate).init(restored["variables"])
    legacy = (
        legacy[0]._replace(
            count=restored["opt"].count,
            mu=restored["opt"].m,
            nu=restored["opt"].v,
        ),
    ) + tuple(legacy[1:])
    shutil.rmtree(orbax_dir)
    checkpointer.save(
        str(orbax_dir),
        {"variables": restored["variables"], "opt": legacy},
        force=True,
    )
    checkpointer.wait_until_finished()

    # Resume from the legacy-layout orbax checkpoint: migrate, not crash.
    out2 = subprocess.run(
        cmd, capture_output=True, text=True, timeout=180, env=env
    )
    assert out2.returncode == 0, out2.stderr[-2000:]
    assert "steps=2" in out2.stdout


def test_transformer_gqa_trains_and_matches_heads():
    """num_kv_heads < num_heads (GQA): model trains with finite grads,
    and the flash path agrees with the dense (repeated-KV) path on the
    same params."""
    import jax
    import jax.numpy as jnp

    from shockwave_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        lm_loss,
    )

    rng = np.random.default_rng(21)
    kw = dict(vocab_size=64, d_model=32, num_heads=4, num_kv_heads=2,
              num_layers=2, d_ff=64, max_len=128)
    cfg = TransformerConfig(attention="flash", **kw)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(rng.integers(0, 64, (2, 129)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])
    loss, grads = jax.value_and_grad(
        lambda p: lm_loss(model, p, tokens)
    )(params)
    assert np.isfinite(float(loss))
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf)))
    # K/V projections actually shrank.
    kshape = jax.tree_util.tree_leaves(
        params["params"]["block_0"]["attention"]["key"]
    )[0].shape
    assert kshape == (32, 16)

    logits_flash = model.apply(params, tokens[:, :-1])
    dense = TransformerLM(TransformerConfig(attention="dense", **kw))
    logits_dense = dense.apply(params, tokens[:, :-1])
    np.testing.assert_allclose(
        np.asarray(logits_flash), np.asarray(logits_dense), rtol=2e-3,
        atol=2e-3,
    )

    import pytest

    # Ulysses is the one path that rejects GQA (its all-to-alls reshard
    # the head dim); the guard fires before the mesh check, so no mesh
    # is needed to exercise it. Ring and dense support GQA.
    with pytest.raises(ValueError, match="num_kv_heads"):
        TransformerLM(
            TransformerConfig(attention="ulysses", **kw)
        ).init(jax.random.PRNGKey(0), tokens[:, :-1])


def test_transformer_rope():
    """RoPE: no positional table in the param tree; flash and dense
    agree on the same params; and rotated position-independent q/k
    produce scores that depend only on the position DIFFERENCE (the
    relative-position property that lets rotary extrapolate)."""
    import jax
    import jax.numpy as jnp

    from shockwave_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        apply_rope,
        lm_loss,
    )

    rng = np.random.default_rng(22)
    kw = dict(vocab_size=64, d_model=32, num_heads=2, num_layers=2,
              d_ff=64, max_len=128, positional="rope")
    cfg = TransformerConfig(attention="flash", **kw)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(rng.integers(0, 64, (2, 129)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])
    flat = jax.tree_util.tree_leaves_with_path(params)
    assert not any("positional" in str(p) for p, _ in flat)
    loss, grads = jax.value_and_grad(
        lambda p: lm_loss(model, p, tokens)
    )(params)
    assert np.isfinite(float(loss))
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.all(np.isfinite(np.asarray(leaf)))

    logits_flash = model.apply(params, tokens[:, :-1])
    dense = TransformerLM(TransformerConfig(attention="dense", **kw))
    logits_dense = dense.apply(params, tokens[:, :-1])
    np.testing.assert_allclose(
        np.asarray(logits_flash), np.asarray(logits_dense), rtol=2e-3,
        atol=2e-3,
    )

    # Relative-position property: broadcast one q vector and one k
    # vector across all positions; after rotation, q_i . k_j must be a
    # function of i - j alone (constant along diagonals).
    qv = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
    kv = jnp.asarray(rng.normal(size=(1, 1, 1, 16)), jnp.float32)
    S = 32
    q = apply_rope(jnp.broadcast_to(qv, (1, S, 1, 16)))
    k = apply_rope(jnp.broadcast_to(kv, (1, S, 1, 16)))
    scores = np.asarray(jnp.einsum("bqhd,bkhd->bqk", q, k))[0]
    for off in (-5, 0, 7):
        diag = np.diagonal(scores, offset=off)
        np.testing.assert_allclose(diag, diag[0], rtol=1e-5, atol=1e-5)


def test_chunked_lm_loss_matches_full():
    """The sequence-chunked head/loss (logits never fully materialized,
    chunk logits recomputed in backward) must match the full-logits
    path — value and gradients."""
    import jax
    import jax.numpy as jnp

    from shockwave_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        lm_loss,
    )

    rng = np.random.default_rng(23)
    cfg = TransformerConfig(vocab_size=64, d_model=32, num_heads=2,
                            num_layers=2, d_ff=64, max_len=64)
    model = TransformerLM(cfg)
    tokens = jnp.asarray(rng.integers(0, 64, (2, 65)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens[:, :-1])

    full, g_full = jax.value_and_grad(
        lambda p: lm_loss(model, p, tokens)
    )(params)
    for chunk in (16, 64):
        ck, g_ck = jax.value_and_grad(
            lambda p: lm_loss(model, p, tokens, logit_chunk=chunk)
        )(params)
        np.testing.assert_allclose(float(ck), float(full), rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g_ck),
                        jax.tree_util.tree_leaves(g_full)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )
    import pytest

    with pytest.raises(ValueError):
        lm_loss(model, params, tokens, logit_chunk=7)


def test_remat_group_matches_ungrouped():
    """remat_group=2: half the checkpoint boundaries, identical math —
    loss and grads must match the per-block remat model on the same
    (renamed) params."""
    import jax
    import jax.numpy as jnp

    from shockwave_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        lm_loss,
    )

    rng = np.random.default_rng(24)
    kw = dict(vocab_size=64, d_model=32, num_heads=2, num_layers=4,
              d_ff=64, max_len=64)
    tokens = jnp.asarray(rng.integers(0, 64, (2, 65)), jnp.int32)

    base = TransformerLM(TransformerConfig(remat=True, **kw))
    p_base = base.init(jax.random.PRNGKey(0), tokens[:, :-1])
    grouped = TransformerLM(
        TransformerConfig(remat=True, remat_group=2, **kw)
    )
    # Rename block_{2g+i} -> group_g/block_i.
    pb = p_base["params"]
    pg = {"params": {
        "embedding": pb["embedding"],
        "positional": pb["positional"],
        "ln_f": pb["ln_f"],
        **{
            f"group_{g}": {
                f"block_{i}": pb[f"block_{2 * g + i}"]
                for i in range(2)
            }
            for g in range(2)
        },
    }}
    l_base, g_base = jax.value_and_grad(
        lambda p: lm_loss(base, p, tokens)
    )(p_base)
    l_grp, g_grp = jax.value_and_grad(
        lambda p: lm_loss(grouped, p, tokens)
    )(pg)
    np.testing.assert_allclose(float(l_grp), float(l_base), rtol=1e-6)
    # Exact leaf-by-leaf comparison through the same rename mapping the
    # params used — a permuted gradient assignment must fail.
    gb = g_base["params"]
    gg = g_grp["params"]
    remapped = {
        "embedding": gb["embedding"],
        "positional": gb["positional"],
        "ln_f": gb["ln_f"],
        **{
            f"group_{g}": {
                f"block_{i}": gb[f"block_{2 * g + i}"]
                for i in range(2)
            }
            for g in range(2)
        },
    }
    flat_a = jax.tree_util.tree_leaves_with_path(remapped)
    flat_b = dict(
        (jax.tree_util.keystr(p), v)
        for p, v in jax.tree_util.tree_leaves_with_path(gg)
    )
    assert len(flat_a) == len(flat_b)
    for path, leaf in flat_a:
        np.testing.assert_allclose(
            np.asarray(flat_b[jax.tree_util.keystr(path)]),
            np.asarray(leaf), rtol=1e-4, atol=1e-6,
        )

    import pytest

    with pytest.raises(ValueError):
        TransformerLM(
            TransformerConfig(remat=True, remat_group=3, **kw)
        ).init(jax.random.PRNGKey(0), tokens[:, :-1])


def test_checkpoint_save_is_atomic_and_corrupt_file_fails_loudly(tmp_path):
    """A preemption kill can land mid-save; the save must go through a
    temp file + os.replace so the previous good checkpoint survives a
    torn write (observed live on the packed-pair chip demo: a torn
    msgpack poisoned every retry). A genuinely corrupt checkpoint must
    fail the attempt loudly (nonzero exit -> the scheduler's
    failure/retry path), never silently train from zeros."""
    import os

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    cmd = [
        sys.executable, "-m", "shockwave_tpu.models.train",
        "--model", "Recommendation", "--batch_size", "8", "-n", "2",
        "--checkpoint_dir", str(tmp_path),
    ]
    out1 = subprocess.run(
        cmd, capture_output=True, text=True, timeout=180, env=env
    )
    assert out1.returncode == 0, out1.stderr
    ckpt = tmp_path / "train_state.msgpack"
    good = ckpt.read_bytes()

    # A stale partial temp file (simulated mid-write kill) must not
    # affect the resume: the final path still holds the good bytes.
    (tmp_path / "train_state.msgpack.tmp").write_bytes(good[: len(good) // 3])
    out2 = subprocess.run(
        cmd, capture_output=True, text=True, timeout=180, env=env
    )
    assert out2.returncode == 0, out2.stderr
    # The completed run's save replaces the temp file atomically.
    assert not (tmp_path / "train_state.msgpack.tmp").exists()

    # Truncate the real checkpoint: the attempt must die loudly.
    ckpt.write_bytes(good[: len(good) // 3])
    out3 = subprocess.run(
        cmd, capture_output=True, text=True, timeout=180, env=env
    )
    assert out3.returncode != 0
