"""Scale-proof telemetry primitives (PR 19): sketch quantiles,
cardinality governor, exemplar reservoirs, ring-buffer history, and the
binary sketch-frame fleet-merge path.

Every bound claimed in docs/USAGE.md "Telemetry at scale" is asserted
here: the sketch's relative-error guarantee on adversarial
distributions, exact merges, the per-family series budget with loud
overflow, remove() sweeping sketch/rollup families, and fleet merge
over SKF1 frames agreeing with an offline merge of the same snapshots.
"""

import gzip
import zlib

import numpy as np
import pytest

from shockwave_tpu import obs
from shockwave_tpu.obs.fleet import FleetTelemetry
from shockwave_tpu.obs.history import ExemplarReservoir, RingHistory
from shockwave_tpu.obs.metrics import (
    DROPPED_FAMILY,
    MetricsRegistry,
    merge_snapshots,
    merged_histogram_quantile,
    render_snapshot_text,
    series_quantile,
)
from shockwave_tpu.obs.sketch import (
    FRAME_MAGIC,
    QuantileSketch,
    decode_snapshot_frame,
    encode_snapshot_frame,
    merge_sketch_dicts,
)


@pytest.fixture(autouse=True)
def clean_obs():
    obs.reset()
    yield
    obs.reset()


# ----------------------------------------------------------------------
# QuantileSketch: the alpha relative-error contract.
# ----------------------------------------------------------------------
class TestQuantileSketch:
    # Adversarial shapes: heavy tail, uniform, bimodal with a 6-decade
    # spread, and near-constant (every value in one log bin).
    DISTRIBUTIONS = {
        "lognormal_heavy_tail": lambda rng: rng.lognormal(2.0, 1.5, 20_000),
        "uniform": lambda rng: rng.uniform(0.5, 500.0, 20_000),
        "bimodal_wide": lambda rng: np.concatenate(
            [rng.uniform(1e-3, 2e-3, 10_000), rng.uniform(1e3, 2e3, 10_000)]
        ),
        "near_constant": lambda rng: 42.0 + rng.uniform(0, 1e-6, 20_000),
    }

    @pytest.mark.parametrize("dist", sorted(DISTRIBUTIONS))
    @pytest.mark.parametrize("q", [0.5, 0.9, 0.99, 0.999])
    def test_relative_error_bound(self, dist, q):
        values = self.DISTRIBUTIONS[dist](np.random.default_rng(7))
        sk = QuantileSketch(alpha=0.01)
        sk.add_many(values)
        # The sketch guarantee is RANK-based (the value at the
        # ceil(q*n)-th order statistic), so compare against the
        # non-interpolating quantile — linear interpolation between
        # order statistics is meaningless across a bimodal gap.
        exact = float(np.quantile(values, q, method="inverted_cdf"))
        est = sk.quantile(q)
        # 2*alpha/(1-alpha) ~ the worst-case bound; 2.5*alpha is the
        # round number the smoke gate and docs pin.
        assert abs(est - exact) / abs(exact) <= 2.5 * sk.alpha, (
            dist, q, est, exact,
        )

    def test_add_many_matches_scalar_adds(self):
        values = np.random.default_rng(3).lognormal(1.0, 1.0, 5_000)
        batch, scalar = QuantileSketch(), QuantileSketch()
        batch.add_many(values)
        for v in values:
            scalar.add(float(v))
        got, want = batch.to_dict(), scalar.to_dict()
        # numpy's pairwise summation differs from sequential adds in
        # the last ulp; everything discrete must match exactly.
        assert got.pop("sum") == pytest.approx(want.pop("sum"))
        assert got == want

    def test_negative_zero_and_mixed_sign(self):
        # The calibration plane's signed forecast error crosses zero.
        sk = QuantileSketch(alpha=0.01)
        values = [-100.0, -1.0, 0.0, 0.0, 1.0, 100.0]
        for v in values:
            sk.add(v)
        assert sk.count == 6
        assert sk.zero_count == 2
        assert sk.quantile(0.0) == -100.0
        assert sk.quantile(1.0) == 100.0
        med = sk.quantile(0.5)
        assert -1.0 <= med <= 0.0

    def test_empty_sketch_quantile_is_none(self):
        assert QuantileSketch().quantile(0.99) is None

    def test_merge_is_exact(self):
        # The fleet-merge guarantee: merging two sketches is
        # bit-identical to one sketch having seen both streams.
        rng = np.random.default_rng(11)
        a_vals = rng.lognormal(2.0, 1.0, 4_000)
        b_vals = rng.uniform(0.1, 50.0, 4_000)
        a, b, one = QuantileSketch(), QuantileSketch(), QuantileSketch()
        a.add_many(a_vals)
        b.add_many(b_vals)
        one.add_many(np.concatenate([a_vals, b_vals]))
        assert a.merge(b).to_dict() == one.to_dict()

    def test_merge_alpha_mismatch_raises(self):
        with pytest.raises(ValueError):
            QuantileSketch(alpha=0.01).merge(QuantileSketch(alpha=0.02))

    def test_memory_bound_collapses_cheap_end_only(self):
        # lognormal(0, 2) spans ~e^-8..e^8: ~780 natural bins at
        # alpha=0.01, so a 256-bin cap forces collapsing — but only of
        # the LOWEST bins, all below the p99 bin, so the tail keeps
        # its alpha guarantee.
        sk = QuantileSketch(alpha=0.01, max_bins=256)
        values = np.random.default_rng(5).lognormal(0.0, 2.0, 50_000)
        sk.add_many(values)
        assert len(sk._pos) <= 256
        exact = float(np.quantile(values, 0.99, method="inverted_cdf"))
        assert abs(sk.quantile(0.99) - exact) / exact <= 2.5 * sk.alpha

    def test_dict_roundtrip(self):
        sk = QuantileSketch()
        sk.add_many([-3.0, 0.0, 1.0, 2.5, 1e6])
        assert QuantileSketch.from_dict(sk.to_dict()).to_dict() == sk.to_dict()

    def test_merge_sketch_dicts_skips_empties(self):
        a = QuantileSketch()
        a.add(5.0)
        merged = merge_sketch_dicts([None, {}, a.to_dict()])
        assert merged.count == 1
        assert merge_sketch_dicts([None, {}]) is None


# ----------------------------------------------------------------------
# SKF1 snapshot frames.
# ----------------------------------------------------------------------
class TestSnapshotFrames:
    def test_roundtrip(self):
        snap = {"schema": "x", "metrics": {"a": {"series": []}}, "extra": 1}
        frame = encode_snapshot_frame(snap)
        assert frame.startswith(FRAME_MAGIC)
        assert decode_snapshot_frame(frame) == snap

    @pytest.mark.parametrize(
        "junk",
        [
            b"",
            b"not a frame",
            FRAME_MAGIC + b"garbage-not-zlib",
            FRAME_MAGIC + zlib.compress(b"[1, 2, 3]"),  # JSON, not a dict
            encode_snapshot_frame({"ok": True})[:-3],  # truncated push
        ],
    )
    def test_malformed_frames_decode_to_none(self, junk):
        assert decode_snapshot_frame(junk) is None


# ----------------------------------------------------------------------
# Cardinality governor.
# ----------------------------------------------------------------------
class TestCardinalityGovernor:
    def test_budget_held_and_overflow_loud(self):
        reg = MetricsRegistry(enabled=True, max_series=16)
        g = reg.gauge("job_progress", "per-job flood")
        for j in range(1_000):
            g.set(float(j), job_id=str(j))
        snap = reg.snapshot()["metrics"]
        fam = snap["job_progress"]["series"]
        assert len(fam) <= 16
        overflow = [
            s for s in fam if s["labels"].get("overflow") == "true"
        ]
        assert overflow, "over-budget traffic must fold into overflow"
        dropped = snap[DROPPED_FAMILY]["series"]
        assert dropped and dropped[0]["labels"]["metric"] == "job_progress"
        assert dropped[0]["value"] > 0
        assert 'overflow="true"' in reg.render_text()

    def test_env_budget_knob(self, monkeypatch):
        monkeypatch.setenv("SHOCKWAVE_METRICS_MAX_SERIES", "9")
        assert MetricsRegistry(enabled=True).series_budget() == 9

    def test_governor_decay_readmits_after_idle_fold(self):
        reg = MetricsRegistry(enabled=True, max_series=8)
        g = reg.gauge("g", "")
        for j in range(8):
            g.set(1.0, job_id=str(j))
        # Budget full: a cold tick folds idle series, opening slots.
        for _ in range(4):
            reg.scale_tick(0.0)
        g.set(1.0, job_id="fresh")
        series = reg.snapshot()["metrics"]["g"]["series"]
        labels = [s["labels"] for s in series]
        assert {"job_id": "fresh"} in labels
        assert len(series) <= 8

    def test_overflow_histogram_keeps_observing(self):
        reg = MetricsRegistry(enabled=True, max_series=4)
        h = reg.histogram("h", "")
        for j in range(64):
            h.observe(float(j + 1), job_id=str(j))
        metric = reg.snapshot()["metrics"]["h"]
        total = sum(s["count"] for s in metric["series"])
        assert total == 64, "dropped ROUTINGS must still be counted"

    def test_remove_sweeps_sketch_and_rollup_families(self):
        reg = MetricsRegistry(enabled=True)
        reg.gauge("worker_clock", "").set(1.0, worker="w0")
        reg.gauge("worker_clock", "").set(1.0, worker="w1")
        reg.histogram("worker_lat", "").observe(0.5, worker="w0")
        removed = reg.remove_series(worker="w0")
        assert removed == 2
        text = reg.render_text()
        assert 'worker="w0"' not in text
        assert 'worker="w1"' in text


# ----------------------------------------------------------------------
# RingHistory / ExemplarReservoir.
# ----------------------------------------------------------------------
class TestRingHistory:
    def test_fixed_memory_and_coarse_rollup(self):
        ring = RingHistory(raw_len=8, coarse_len=4, per_coarse=4)
        for i in range(100):
            ring.append(float(i), float(i % 10))
        snap = ring.snapshot()
        assert snap["samples"] == 100
        assert len(snap["raw"]) == 8
        assert len(snap["coarse"]) == 4
        # Raw keeps the newest window, oldest-first.
        assert [t for t, _ in snap["raw"]] == [float(i) for i in range(92, 100)]
        for t_last, lo, hi, mean in snap["coarse"]:
            assert lo <= mean <= hi

    def test_coarse_point_aggregates_per_coarse_raw(self):
        ring = RingHistory(raw_len=16, coarse_len=8, per_coarse=4)
        for i, v in enumerate([1.0, 9.0, 5.0, 5.0]):
            ring.append(float(i), v)
        (point,) = ring.snapshot()["coarse"]
        assert point == [3.0, 1.0, 9.0, 5.0]


class TestExemplarReservoir:
    def test_keeps_top_k_by_score_with_identity(self):
        res = ExemplarReservoir(k=3)
        for j in range(100):
            res.offer(f"job-{j}", float(j), cell="c0")
        top = res.entries()
        assert [e[0] for e in top] == ["job-99", "job-98", "job-97"]
        assert res.offered == 100
        assert len(res) == 3
        assert res.snapshot()["entries"][0] == {
            "id": "job-99", "score": 99.0, "cell": "c0",
        }

    def test_refresh_and_remove(self):
        res = ExemplarReservoir(k=2)
        res.offer("a", 10.0)
        res.offer("b", 20.0)
        assert not res.offer("c", 5.0)
        assert res.offer("a", 1.0), "existing id refreshes, newest wins"
        assert res.evicted_by("d", 30.0) == "a"
        res.remove("b")
        assert "b" not in res
        assert len(res) == 1


# ----------------------------------------------------------------------
# Sketch-backed registry quantiles + fleet merge.
# ----------------------------------------------------------------------
class TestSketchQuantiles:
    def test_series_quantile_prefers_sketch_over_buckets(self):
        reg = MetricsRegistry(enabled=True)
        values = np.random.default_rng(2).lognormal(2.0, 1.0, 10_000)
        reg.histogram("h", "").observe_many(values)
        (series,) = reg.snapshot()["metrics"]["h"]["series"]
        est, count = series_quantile(series, 0.99)
        exact = float(np.quantile(values, 0.99))
        assert count == 10_000
        assert abs(est - exact) / exact <= 2.5 * reg.sketch_alpha

    def test_merged_quantile_without_sketch_falls_back_to_buckets(self):
        # A legacy snapshot (no "sketch" key) must still yield a
        # bucket-interpolated answer, not a crash.
        reg = MetricsRegistry(enabled=True)
        reg.histogram("h", "").observe_many([0.1, 0.2, 0.4, 0.8])
        metric = reg.snapshot()["metrics"]["h"]
        for series in metric["series"]:
            series.pop("sketch", None)
        est, count = merged_histogram_quantile(metric, 0.5)
        assert count == 4
        assert est is not None and est > 0

    def test_fleet_frame_merge_equals_offline_merge(self):
        rng = np.random.default_rng(9)
        regs = []
        for _ in range(4):
            reg = MetricsRegistry(enabled=True)
            reg.histogram("worker_job_seconds", "").observe_many(
                rng.lognormal(2.0, 1.0, 2_000)
            )
            regs.append(reg)

        fleet = FleetTelemetry()
        for i, reg in enumerate(regs):
            label = f"worker-{i}"
            fleet.add_target(label, lambda: "")
            assert fleet.accept_frame(
                label, encode_snapshot_frame(reg.snapshot())
            )
        offline = merge_snapshots([r.snapshot() for r in regs])
        via_fleet = fleet.merged_snapshot()
        for q in (0.5, 0.9, 0.99):
            a, na = merged_histogram_quantile(
                offline["metrics"]["worker_job_seconds"], q
            )
            b, nb = merged_histogram_quantile(
                via_fleet["metrics"]["worker_job_seconds"], q
            )
            assert na == nb == 8_000
            assert a == pytest.approx(b)

    def test_fleet_rejects_unknown_label_and_malformed_frame(self):
        fleet = FleetTelemetry()
        fleet.add_target("w0", lambda: "")
        frame = encode_snapshot_frame(
            MetricsRegistry(enabled=True).snapshot()
        )
        assert not fleet.accept_frame("retired-worker", frame)
        assert not fleet.accept_frame("w0", b"not a frame")
        # Retirement drops the label's buffered snapshot too.
        assert fleet.accept_frame("w0", frame)
        fleet.remove_target("w0")
        assert not fleet.accept_frame("w0", frame)

    def test_render_snapshot_text_gzips_cleanly(self):
        reg = MetricsRegistry(enabled=True)
        reg.counter("c", "help").inc(5.0, cell="c0")
        reg.histogram("h", "help").observe_many([0.5, 1.5])
        text = render_snapshot_text(reg.snapshot())
        assert "# TYPE c counter" in text
        assert 'h_bucket{le="+Inf"} 2' in text
        blob = gzip.compress(text.encode("utf-8"), 6)
        assert gzip.decompress(blob).decode("utf-8") == text


# ----------------------------------------------------------------------
# Calibration rollup + worst-offender eviction.
# ----------------------------------------------------------------------
class TestCalibrationEviction:
    def test_per_job_stats_survive_only_for_reservoir_members(self):
        obs.configure(metrics=True)
        cal = obs.get_calibration()
        cal.enabled = True
        for j in range(200):
            # MAPE grows with j: the last k jobs are the worst.
            cal.record_forecast(f"j{j}", 0.0, 100.0 + j)
            cal.record_outcome(f"j{j}", 100.0)
        snap = cal.snapshot()
        assert snap["fleet"]["forecasts"] == 200
        assert 0 < len(snap["jobs"]) <= 10
        assert "j199" in snap["jobs"]
        assert "j0" not in snap["jobs"]
