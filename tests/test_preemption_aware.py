"""Preemption-aware planning: overhead resolution, round auto-sizing,
and the scheduler/planner integration of the switching-cost term."""

import numpy as np
import pytest

from shockwave_tpu.core.scheduler import (
    Scheduler,
    autosize_round_duration,
    resolve_preemption_overhead,
)
from shockwave_tpu.data.default_oracle import generate_oracle
from shockwave_tpu.data.profiles import synthesize_profiles
from shockwave_tpu.policies import get_policy
from tests.test_simulator import tiny_trace


class TestResolveOverhead:
    def test_none_is_zero(self):
        assert resolve_preemption_overhead(None, "ResNet-18 (batch size 32)") == 0.0

    def test_scalar_applies_to_every_family(self):
        assert resolve_preemption_overhead(42, "LM (batch size 5)") == 42.0

    def test_family_lookup_strips_batch_suffix(self):
        table = {"ResNet-18": 90.0, "LM": 30.0}
        assert (
            resolve_preemption_overhead(table, "ResNet-18 (batch size 32)")
            == 90.0
        )
        assert resolve_preemption_overhead(table, "LM (batch size 5)") == 30.0

    def test_absent_family_falls_back_to_default_then_zero(self):
        table = {"ResNet-18": 90.0, "default": 12.0}
        assert (
            resolve_preemption_overhead(table, "Transformer (batch size 8)")
            == 12.0
        )
        assert (
            resolve_preemption_overhead({"LM": 5.0}, "Transformer (batch size 8)")
            == 0.0
        )


class TestAutosizeRound:
    def test_no_overheads_keeps_base(self):
        assert autosize_round_duration(None, 60.0) == 60.0
        assert autosize_round_duration({}, 60.0) == 60.0

    def test_scalar_overhead_sizes_to_fraction(self):
        # 90 s overhead at <= 25% of a round needs a 360 s round.
        assert autosize_round_duration(90.0, 60.0, 0.25) == 360.0

    def test_dict_uses_worst_family(self):
        table = {"LM": 30.0, "ResNet-18": 90.0, "default": 10.0}
        assert autosize_round_duration(table, 60.0, 0.5) == 180.0

    def test_never_shrinks_below_base(self):
        assert autosize_round_duration(5.0, 60.0, 0.5) == 60.0

    def test_cap_bounds_the_stretch(self):
        assert (
            autosize_round_duration(1000.0, 60.0, 0.1, max_round_s=600.0)
            == 600.0
        )

    def test_invalid_fraction_raises(self):
        with pytest.raises(ValueError):
            autosize_round_duration(90.0, 60.0, 0.0)
        with pytest.raises(ValueError):
            autosize_round_duration(90.0, 60.0, 1.5)


def run_shockwave_sim(
    jobs, arrivals, num_gpus=2, preemption_overheads=None,
    round_overhead_fraction=None, round_s=120,
):
    oracle = generate_oracle()
    profiles = synthesize_profiles(jobs, oracle)
    config = {
        "num_gpus": num_gpus,
        "time_per_iteration": round_s,
        "future_rounds": 6,
        "lambda": 2.0,
        "k": 1e-3,
        "solver_rel_gap": 1e-3,
        "solver_timeout": 15,
    }
    sched = Scheduler(
        get_policy("shockwave_tpu"),
        throughputs=oracle,
        seed=0,
        time_per_iteration=round_s,
        profiles=profiles,
        shockwave_config=config,
        preemption_overheads=preemption_overheads,
        round_overhead_fraction=round_overhead_fraction,
    )
    makespan = sched.simulate({"v100": num_gpus}, list(arrivals), list(jobs))
    return sched, makespan


def test_scheduler_autosizes_round_and_planner_config():
    jobs, arrivals = tiny_trace(num_jobs=3, epochs=2)
    sched, makespan = run_shockwave_sim(
        jobs,
        arrivals,
        preemption_overheads={"ResNet-18": 90.0},
        round_overhead_fraction=0.25,
    )
    # 90 s / 0.25 = 360 s round (base 120 s stretched, never shrunk).
    assert sched._time_per_iteration == 360.0
    assert sched._shockwave.round_duration == 360.0
    assert makespan > 0
    assert len(sched._job_completion_times) == len(jobs)


# The measured per-family relaunch bill of the committed physical TPU
# run (results/physical_tpu/shockwave_tpu/summary.json, via
# overheads_from_phase_report): sum of mean rendezvous + build +
# restore + first-step-compile + save per attempt.
MEASURED_OVERHEADS = {
    "LM": 32.4,
    "Recommendation": 32.6,
    "ResNet-18": 92.8,
    "ResNet-50": 99.1,
    "Transformer": 31.8,
}


def test_overheads_from_phase_report_matches_committed_run():
    """The driver's overhead derivation, applied to the committed
    physical-TPU phase report, reproduces the table above: every
    relaunch phase (rendezvous/build/restore/first-step-compile/save)
    counted once, `train` (the useful work) excluded."""
    import json
    import os

    from scripts.drivers.physical_common import overheads_from_phase_report

    summary = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "results",
        "physical_tpu",
        "shockwave_tpu",
        "summary.json",
    )
    with open(summary) as f:
        report = json.load(f)["preemption_overhead_phases"]
    assert overheads_from_phase_report(report) == MEASURED_OVERHEADS
    # Families with no relaunch bill are omitted, not reported as 0.
    assert overheads_from_phase_report(
        {"Idle": {"attempts": 1, "train_mean_s": 9.0}}
    ) == {}


def run_trace_sim(preemption_overheads=None, num_gpus=2, round_s=60):
    import os

    from shockwave_tpu.data import parse_trace
    from shockwave_tpu.data.profiles import synthesize_profiles as synth

    trace = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "traces",
        "small_12_dynamic.trace",
    )
    jobs, arrivals = parse_trace(trace)
    oracle = generate_oracle()
    profiles = synth(jobs, oracle)
    for i, job in enumerate(jobs):
        job.duration = sum(profiles[i]["duration_every_epoch"])
    config = {
        "num_gpus": num_gpus,
        "time_per_iteration": round_s,
        "future_rounds": 20,
        "lambda": 5.0,
        "k": 10.0,
        "solver_rel_gap": 1e-3,
        "solver_timeout": 15,
    }
    sched = Scheduler(
        get_policy("shockwave_tpu"),
        throughputs=oracle,
        seed=0,
        time_per_iteration=round_s,
        profiles=profiles,
        shockwave_config=config,
        preemption_overheads=preemption_overheads,
    )
    sched.simulate({"v100": num_gpus}, list(arrivals), list(jobs))
    return sched


def test_measured_overheads_reduce_preemptions_on_12_job_trace():
    """The headline acceptance property: charging the measured per-family
    relaunch bill reduces preemption count on the 12-job trace at
    equal-or-better worst-FTF versus the overhead-blind planner."""
    blind = run_trace_sim()
    aware = run_trace_sim(preemption_overheads=dict(MEASURED_OVERHEADS))
    assert len(aware._job_completion_times) == 12
    assert aware.get_num_preemptions() < blind.get_num_preemptions()
    blind_ftf, blind_unfair = blind.get_finish_time_fairness()
    aware_ftf, aware_unfair = aware.get_finish_time_fairness()
    assert max(aware_ftf) <= max(blind_ftf) + 1e-9
    assert aware_unfair <= blind_unfair + 1e-9


def test_zero_overhead_table_reproduces_blind_run_exactly():
    """An all-zero overhead table must leave the whole simulation — plan,
    preemptions, makespan — bit-identical to the overhead-blind run."""
    jobs, arrivals = tiny_trace(num_jobs=4, epochs=2, arrival_gap=50.0)
    blind_sched, blind_makespan = run_shockwave_sim(list(jobs), arrivals)

    jobs2, _ = tiny_trace(num_jobs=4, epochs=2, arrival_gap=50.0)
    zero_sched, zero_makespan = run_shockwave_sim(
        list(jobs2), arrivals, preemption_overheads={"ResNet-18": 0.0}
    )
    assert zero_makespan == blind_makespan
    assert (
        zero_sched.get_num_preemptions() == blind_sched.get_num_preemptions()
    )
    assert dict(zero_sched._job_completion_times) == dict(
        blind_sched._job_completion_times
    )
