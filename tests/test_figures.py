"""The evaluation-panel pipeline (scripts/analysis/figures.py) renders
from committed summary.json files alone — smoke-tested here against a
synthetic results tree so the committed panel stays reproducible."""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scripts.analysis.figures import TIER_ORDER, load_tiers, plot  # noqa: E402


def _fake_tier(path, sizes, policies):
    os.makedirs(path, exist_ok=True)
    results = {}
    for s in sizes:
        for i, p in enumerate(policies):
            results[f"{p}_{s}gpus"] = {
                "policy": p,
                "num_gpus": str(s),
                "makespan": 1000.0 * (i + 1),
                "avg_jct": 100.0 * (i + 1),
                "worst_ftf": 1.0 + i,
                "unfair_fraction": 5.0 * i,
                "utilization": 0.5,
                "rounds": 10,
                "sim_wall_clock_s": 1.0,
            }
    with open(os.path.join(path, "summary.json"), "w") as f:
        json.dump({"trace": "fake.trace", "results": results}, f)


def test_panel_renders_from_summaries(tmp_path):
    _fake_tier(
        str(tmp_path / "scale"), [64, 128],
        ["max_min_fairness", "shockwave_tpu"],
    )
    _fake_tier(
        str(tmp_path / "scale_tpu"), [32],
        ["max_min_fairness", "finish_time_fairness", "shockwave_tpu"],
    )
    tiers = load_tiers(str(tmp_path))
    # Only the tiers present are loaded, in TIER_ORDER order.
    assert list(tiers) == ["scale", "scale_tpu"]
    assert all(name in TIER_ORDER for name in tiers)
    out = str(tmp_path / "panel.png")
    plot(tiers, out)
    assert os.path.exists(out)
    assert os.path.getsize(out) > 10_000  # a real rendered image


def test_missing_results_dir_loads_nothing(tmp_path):
    assert load_tiers(str(tmp_path / "nope")) == {}
