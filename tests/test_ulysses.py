"""Ulysses (all-to-all) sequence parallelism must match dense causal
attention exactly, like ring attention does, including through grads and
with the flash kernel as the local attention."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shockwave_tpu.parallel.mesh import make_mesh
from shockwave_tpu.parallel.ring_attention import dense_causal_attention
from shockwave_tpu.parallel.ulysses import ulysses_attention


def _qkv(rng, B, S, H, D):
    return tuple(
        jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("seq_shards", [2, 4])
def test_matches_dense_attention(seq_shards):
    mesh = make_mesh((1, 1, seq_shards), devices=jax.devices()[:seq_shards])
    q, k, v = _qkv(np.random.default_rng(0), 2, 8 * seq_shards, seq_shards, 4)
    out = ulysses_attention(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(dense_causal_attention(q, k, v)),
        rtol=2e-4,
        atol=2e-5,
    )


def test_combined_data_model_seq_mesh():
    # heads are tensor-parallel over "model" AND all-to-all'd over "seq":
    # 4 heads / model=2 -> 2 local heads, divisible by seq=2.
    mesh = make_mesh((2, 2, 2))
    q, k, v = _qkv(np.random.default_rng(1), 4, 16, 4, 8)
    out = ulysses_attention(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(dense_causal_attention(q, k, v)),
        rtol=2e-4,
        atol=2e-5,
    )


@pytest.mark.parametrize("S", [128, 16])
def test_flash_local_attention(S):
    # S=128 runs the Pallas kernel on each device's gathered sequence;
    # S=16 doesn't tile into the kernel's blocks and must fall back to
    # the dense local path. Grads go through the kernel's custom_vjp
    # under shard_map — the exact composition the model ships.
    mesh = make_mesh((1, 1, 2), devices=jax.devices()[:2])
    q, k, v = _qkv(np.random.default_rng(2), 1, S, 2, 8)

    def loss(fn):
        return lambda q: jnp.sum(fn(q) ** 2)

    uly = lambda q: ulysses_attention(q, k, v, mesh, local_attention="flash")
    dense = lambda q: dense_causal_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(uly(q)), np.asarray(dense(q)), rtol=2e-4, atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(jax.grad(loss(uly))(q)),
        np.asarray(jax.grad(loss(dense))(q)),
        rtol=1e-3,
        atol=1e-4,
    )


@pytest.mark.slow
def test_grad_matches_dense():
    mesh = make_mesh((1, 1, 2), devices=jax.devices()[:2])
    q, k, v = _qkv(np.random.default_rng(3), 1, 8, 2, 4)

    g_uly = jax.grad(lambda q: jnp.sum(ulysses_attention(q, k, v, mesh) ** 2))(q)
    g_dense = jax.grad(
        lambda q: jnp.sum(dense_causal_attention(q, k, v) ** 2)
    )(q)
    np.testing.assert_allclose(
        np.asarray(g_uly), np.asarray(g_dense), rtol=1e-3, atol=1e-4
    )


def test_indivisible_heads_rejected():
    mesh = make_mesh((1, 1, 4), devices=jax.devices()[:4])
    q, k, v = _qkv(np.random.default_rng(4), 1, 16, 2, 4)
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention(q, k, v, mesh)
