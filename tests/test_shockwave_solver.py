"""Solver tests: the exact MILP against brute force on tiny instances, and
the TPU (relaxed JAX + rounding) backend against the MILP — the
solver-vs-solver agreement layer the reference lacks (SURVEY §4)."""

import itertools

import numpy as np
import pytest

from shockwave_tpu.solver.eg_jax import solve_eg_greedy, solve_eg_jax
from shockwave_tpu.solver.eg_milp import reorder_unfair_jobs_milp, solve_eg_milp
from shockwave_tpu.solver.eg_problem import EGProblem
from shockwave_tpu.solver.rounding import (
    order_schedule,
    reorder_columns,
    round_counts,
    schedule_from_relaxed,
)

LOG_BASES = np.array([0.0, 0.2, 0.4, 0.6, 0.8, 1.0])


def make_problem(
    priorities, completed, total, epoch_dur, remaining, nworkers,
    num_gpus=2, round_duration=100.0, future_rounds=3, regularizer=0.001,
):
    return EGProblem(
        priorities=np.asarray(priorities, dtype=np.float64),
        completed_epochs=np.asarray(completed, dtype=np.float64),
        total_epochs=np.asarray(total, dtype=np.float64),
        epoch_duration=np.asarray(epoch_dur, dtype=np.float64),
        remaining_runtime=np.asarray(remaining, dtype=np.float64),
        nworkers=np.asarray(nworkers, dtype=np.float64),
        num_gpus=num_gpus,
        round_duration=round_duration,
        future_rounds=future_rounds,
        regularizer=regularizer,
    log_bases=LOG_BASES,
    )


def brute_force_best(problem):
    J, R = problem.num_jobs, problem.future_rounds
    best, best_Y = -np.inf, None
    for bits in itertools.product([0, 1], repeat=J * R):
        Y = np.array(bits).reshape(J, R)
        loads = problem.nworkers @ Y
        if np.any(loads > problem.num_gpus):
            continue
        v = problem.objective_value(Y)
        if v > best:
            best, best_Y = v, Y
    return best, best_Y


def random_problem(rng, J=4, R=3, num_gpus=3):
    total = rng.integers(2, 10, J).astype(float)
    completed = np.floor(total * rng.uniform(0, 0.9, J))
    epoch_dur = rng.uniform(30, 300, J)
    remaining = (total - completed) * epoch_dur * rng.uniform(0.8, 1.2, J)
    return make_problem(
        priorities=rng.uniform(0.5, 4.0, J),
        completed=completed,
        total=total,
        epoch_dur=epoch_dur,
        remaining=remaining,
        nworkers=rng.integers(1, 3, J).astype(float),
        num_gpus=num_gpus,
        round_duration=100.0,
        future_rounds=R,
        regularizer=1e-4,
    )


class TestMilpBackend:
    @pytest.mark.parametrize("seed", range(5))
    def test_milp_matches_brute_force(self, seed):
        rng = np.random.default_rng(seed)
        problem = random_problem(rng)
        best, _ = brute_force_best(problem)
        Y = solve_eg_milp(problem, rel_gap=1e-9, time_limit=30)
        loads = problem.nworkers @ Y
        assert np.all(loads <= problem.num_gpus + 1e-9)
        assert problem.objective_value(Y) == pytest.approx(best, abs=1e-6)

    def test_reorder_preserves_counts_and_capacity(self):
        rng = np.random.default_rng(7)
        problem = random_problem(rng, J=5, R=4)
        Y = solve_eg_milp(problem)
        Y2 = reorder_unfair_jobs_milp(Y, problem)
        np.testing.assert_array_equal(Y.sum(axis=1), Y2.sum(axis=1))
        assert np.all(problem.nworkers @ Y2 <= problem.num_gpus + 1e-9)
        # The reorder can only improve its own objective.
        assert problem.reorder_objective(Y2) <= problem.reorder_objective(Y) + 1e-9


class TestRounding:
    def test_round_counts_respects_budget(self):
        s = np.array([2.7, 1.6, 0.4, 3.0])
        g = np.array([1.0, 2.0, 1.0, 1.0])
        n = round_counts(s, g, num_gpus=2, future_rounds=3)
        assert np.sum(g * n) <= 2 * 3
        assert np.all(n <= 3)

    def test_order_schedule_capacity_and_counts(self):
        counts = np.array([3, 2, 1])
        p = np.array([5.0, 1.0, 3.0])
        g = np.array([1.0, 1.0, 2.0])
        Y = order_schedule(counts, p, g, num_gpus=3, future_rounds=3)
        np.testing.assert_array_equal(Y.sum(axis=1), counts)
        assert np.all(g @ Y <= 3)

    def test_high_priority_jobs_scheduled_earliest(self):
        counts = np.array([1, 1])
        p = np.array([1.0, 10.0])
        g = np.array([1.0, 1.0])
        Y = order_schedule(counts, p, g, num_gpus=1, future_rounds=2)
        # Job 1 (priority 10) gets round 0; job 0 waits.
        assert Y[1, 0] == 1 and Y[0, 1] == 1


class TestTpuBackend:
    @pytest.mark.parametrize("seed", range(8))
    def test_rounded_schedule_near_milp_quality(self, seed):
        rng = np.random.default_rng(100 + seed)
        problem = random_problem(rng, J=6, R=4, num_gpus=3)
        Y_milp = solve_eg_milp(problem, rel_gap=1e-6, time_limit=30)
        Y_tpu = reorder_columns(solve_eg_greedy(problem), problem.priorities)
        assert np.all(problem.nworkers @ Y_tpu <= problem.num_gpus + 1e-9)
        obj_milp = problem.objective_value(Y_milp)
        obj_tpu = problem.objective_value(Y_tpu)
        # Accepted approximation band for the greedy vs the exact boolean
        # optimum (measured: mean gap ~0.01, max ~0.07 over 40 seeds).
        scale = max(1.0, abs(obj_milp))
        assert obj_tpu >= obj_milp - 0.08 * scale

    @pytest.mark.parametrize("seed", range(8))
    def test_level_schedule_near_milp_quality(self, seed):
        """The level-set solver on tiny instances: feasible and inside the
        same approximation band as the greedy."""
        from shockwave_tpu.solver.eg_jax import solve_eg_level

        rng = np.random.default_rng(100 + seed)
        problem = random_problem(rng, J=6, R=4, num_gpus=3)
        Y_milp = solve_eg_milp(problem, rel_gap=1e-6, time_limit=30)
        Y = solve_eg_level(problem)
        assert np.all(problem.nworkers @ Y <= problem.num_gpus + 1e-9)
        assert np.all(Y.sum(axis=1) <= problem.future_rounds)
        obj_milp = problem.objective_value(Y_milp)
        scale = max(1.0, abs(obj_milp))
        assert problem.objective_value(Y) >= obj_milp - 0.08 * scale

    def test_level_unpackable_counts_fall_back_to_greedy(self):
        """Gang widths that don't tile the cluster: aggregate-feasible
        counts [2, 1] (two width-2 gangs, 3 GPUs, 2 rounds) can only
        place [2, 0]; the level path must not return that starved
        schedule when the packable greedy scores better."""
        from shockwave_tpu.solver.eg_jax import solve_eg_level

        problem = make_problem(
            priorities=[1.0, 1.0],
            completed=[0.0, 0.0],
            total=[10.0, 10.0],
            epoch_dur=[100.0, 100.0],
            remaining=[1000.0, 1000.0],
            nworkers=[2.0, 2.0],
            num_gpus=3,
            round_duration=100.0,
            future_rounds=2,
            regularizer=1.0,
        )
        Y_level = solve_eg_level(problem)
        Y_greedy = solve_eg_greedy(problem)
        assert np.all(problem.nworkers @ Y_level <= problem.num_gpus + 1e-9)
        assert problem.objective_value(Y_level) >= problem.objective_value(
            Y_greedy
        ) - 1e-9
        # Both jobs make progress.
        assert np.all(Y_level.sum(axis=1) >= 1)

    def test_relaxed_solution_feasible(self):
        rng = np.random.default_rng(3)
        problem = random_problem(rng, J=8, R=5, num_gpus=4)
        s = solve_eg_jax(problem)
        assert np.all(s >= -1e-5)
        assert np.all(s <= problem.future_rounds + 1e-5)
        budget = problem.num_gpus * problem.future_rounds
        assert float(problem.nworkers @ s) <= budget * (1 + 1e-4)

    def test_saturated_jobs_get_no_extra_rounds(self):
        # A job that can finish in one round's worth of seconds should not
        # hoard the window when others are starved.
        problem = make_problem(
            priorities=[1.0, 1.0],
            completed=[9.0, 0.0],
            total=[10.0, 10.0],
            epoch_dur=[50.0, 100.0],
            remaining=[50.0, 1000.0],
            nworkers=[1.0, 1.0],
            num_gpus=1,
            round_duration=100.0,
            future_rounds=4,
            regularizer=1e-4,
        )
        s = solve_eg_jax(problem)
        # Job 0 needs 0.5 rounds; job 1 needs 10.
        assert s[0] < 1.5
        assert s[1] > 2.0


class TestReorderRounds:
    """reorder_rounds: the re-placement counterpart of the reference's
    second (unfair-jobs) MILP (reference: shockwave.py:281-328)."""

    def _mid_scale_problem(self, seed=0, J=120, R=20, num_gpus=64):
        rng = np.random.default_rng(seed)
        total = rng.integers(5, 60, J).astype(float)
        completed = np.floor(total * rng.uniform(0, 0.8, J))
        epoch_dur = rng.uniform(60, 2000, J)
        return make_problem(
            priorities=rng.uniform(0.5, 30.0, J) ** 5,
            completed=completed,
            total=total,
            epoch_dur=epoch_dur,
            remaining=(total - completed) * epoch_dur,
            nworkers=rng.choice([1, 1, 1, 2, 2, 4, 8], J).astype(float),
            num_gpus=num_gpus,
            round_duration=120.0,
            future_rounds=R,
            regularizer=10.0,
        )

    @pytest.mark.parametrize("seed", range(4))
    def test_preserves_counts_and_capacity(self, seed):
        from shockwave_tpu.solver.rounding import reorder_rounds

        problem = self._mid_scale_problem(seed)
        Y = solve_eg_greedy(problem)
        Y2 = reorder_rounds(
            Y, problem.priorities, problem.nworkers, problem.num_gpus
        )
        assert (Y2.sum(axis=1) == Y.sum(axis=1)).all()
        assert ((problem.nworkers @ Y2) <= problem.num_gpus + 1e-9).all()

    @pytest.mark.parametrize("seed", range(4))
    def test_matches_reorder_milp_quality(self, seed):
        """At saturating load (the regime the 220-job trace runs in), the
        re-placement must land within 10% of the exact reordering MILP —
        the column-permutation fallback alone is ~8x off (the round-1
        fairness regression this guards against)."""
        from shockwave_tpu.solver.rounding import reorder_rounds

        problem = self._mid_scale_problem(seed)
        Y = solve_eg_greedy(problem)
        ours = problem.reorder_objective(
            reorder_rounds(
                Y, problem.priorities, problem.nworkers, problem.num_gpus
            )
        )
        milp = problem.reorder_objective(
            reorder_unfair_jobs_milp(Y, problem, rel_gap=1e-3, time_limit=15)
        )
        assert ours <= milp * 1.10 + 1e-6


class TestMidScaleQuality:
    """Mid-scale (reference-trace-shaped) solver quality guards: ~120 jobs
    x 20 rounds x 64 GPUs at saturating load, both TPU recovery paths
    within a fixed gap of the exact HiGHS MILP objective."""

    def _problem(self, seed):
        rng = np.random.default_rng(seed)
        J = 120
        total = rng.integers(5, 60, J).astype(float)
        completed = np.floor(total * rng.uniform(0, 0.8, J))
        epoch_dur = rng.uniform(60, 2000, J)
        return make_problem(
            priorities=rng.uniform(0.5, 30.0, J) ** 5,
            completed=completed,
            total=total,
            epoch_dur=epoch_dur,
            remaining=(total - completed) * epoch_dur,
            nworkers=rng.choice([1, 1, 1, 2, 2, 4, 8], J).astype(float),
            num_gpus=64,
            round_duration=120.0,
            future_rounds=20,
            regularizer=10.0,
        )

    @pytest.mark.parametrize("seed", range(3))
    def test_greedy_matches_milp_objective(self, seed):
        problem = self._problem(seed)
        og = problem.objective_value(solve_eg_greedy(problem))
        om = problem.objective_value(
            solve_eg_milp(problem, rel_gap=1e-3, time_limit=30)
        )
        # Objectives are large and negative (makespan-dominated); the
        # greedy must land within 1% of the MILP.
        assert og >= om - 0.01 * abs(om)

    @pytest.mark.parametrize("seed", range(3))
    def test_level_matches_milp_objective(self, seed):
        """The level-set solver (production device path) is held to the
        same 1% bar as the exact-marginal greedy."""
        from shockwave_tpu.solver.eg_jax import solve_eg_level

        problem = self._problem(seed)
        Y = solve_eg_level(problem)
        assert np.all(problem.nworkers @ Y <= problem.num_gpus + 1e-9)
        ol = problem.objective_value(Y)
        om = problem.objective_value(
            solve_eg_milp(problem, rel_gap=1e-3, time_limit=30)
        )
        assert ol >= om - 0.01 * abs(om)

    @pytest.mark.parametrize("seed", range(3))
    def test_relaxed_rounding_matches_milp_objective(self, seed):
        from shockwave_tpu.solver.eg_jax import solve_eg_jax

        problem = self._problem(seed)
        s = solve_eg_jax(problem)
        Y = schedule_from_relaxed(
            s,
            problem.priorities,
            problem.nworkers,
            problem.num_gpus,
            problem.future_rounds,
            problem=problem,
        )
        orelax = problem.objective_value(Y)
        om = problem.objective_value(
            solve_eg_milp(problem, rel_gap=1e-3, time_limit=30)
        )
        # The relaxed path (PGD + rounding + exchange repair with
        # compound one-donor->many-receivers and many-donors->one-receiver
        # escapes) is held to the same 1% bar as the production backends.
        assert orelax >= om - 0.01 * abs(om)


def test_relaxed_backend_end_to_end():
    """shockwave_tpu_relaxed is a first-class selectable backend."""
    from tests.test_simulator import run_sim, tiny_trace
    from shockwave_tpu.policies import get_available_policies

    assert "shockwave_tpu_relaxed" in get_available_policies()
    jobs, arrivals = tiny_trace(num_jobs=5, epochs=2, arrival_gap=30.0)
    from shockwave_tpu.core.scheduler import Scheduler
    from shockwave_tpu.data.default_oracle import generate_oracle
    from shockwave_tpu.data.profiles import synthesize_profiles
    from shockwave_tpu.policies import get_policy

    oracle = generate_oracle()
    profiles = synthesize_profiles(jobs, oracle)
    sched = Scheduler(
        get_policy("shockwave_tpu_relaxed"),
        throughputs=oracle,
        seed=0,
        time_per_iteration=120,
        profiles=profiles,
        shockwave_config={
            "num_gpus": 2,
            "time_per_iteration": 120,
            "future_rounds": 8,
            "lambda": 5.0,
            "k": 10.0,
        },
    )
    makespan = sched.simulate({"v100": 2}, arrivals, jobs)
    assert makespan > 0
    assert len(sched._job_completion_times) == 5
    assert all(t is not None for t in sched._job_completion_times.values())


@pytest.mark.parametrize("seed", range(2))
def test_batched_grants_match_milp_objective(seed):
    """grant_batch > 1 amortizes gain computation across grants; its
    stale-marginal quality loss must stay within the same 1% MILP gap as
    the exact single-grant path."""
    rng = np.random.default_rng(seed)
    J = 120
    total = rng.integers(5, 60, J).astype(float)
    completed = np.floor(total * rng.uniform(0, 0.8, J))
    epoch_dur = rng.uniform(60, 2000, J)
    problem = make_problem(
        priorities=rng.uniform(0.5, 30.0, J) ** 5,
        completed=completed,
        total=total,
        epoch_dur=epoch_dur,
        remaining=(total - completed) * epoch_dur,
        nworkers=rng.choice([1, 1, 1, 2, 2, 4, 8], J).astype(float),
        num_gpus=64,
        round_duration=120.0,
        future_rounds=20,
        regularizer=10.0,
    )
    ob = problem.objective_value(solve_eg_greedy(problem, grant_batch=16))
    om = problem.objective_value(
        solve_eg_milp(problem, rel_gap=1e-3, time_limit=30)
    )
    assert ob >= om - 0.01 * abs(om)
    # Capacity never violated despite batched placement.
    Y = solve_eg_greedy(problem, grant_batch=16)
    assert ((problem.nworkers @ Y) <= problem.num_gpus + 1e-9).all()


class TestScheduleAudit:
    """EGProblem.audit_schedule: the feasibility proof behind the
    headline bench number (bench.py audits every timed schedule)."""

    def _problem(self):
        import bench

        return bench.make_problem(
            num_jobs=40, future_rounds=10, num_gpus=16, seed=0
        )

    def test_accepts_feasible_schedule(self):
        from shockwave_tpu.solver.eg_jax import solve_eg_level

        p = self._problem()
        p.audit_schedule(solve_eg_level(p))

    def test_rejects_double_grant(self):
        p = self._problem()
        Y = np.zeros((p.num_jobs, p.future_rounds), dtype=np.int64)
        Y[0, 0] = 2
        with pytest.raises(AssertionError, match="non-boolean"):
            p.audit_schedule(Y)

    def test_rejects_oversubscribed_round(self):
        p = self._problem()
        Y = np.zeros((p.num_jobs, p.future_rounds), dtype=np.int64)
        Y[:, 0] = 1  # every gang in round 0 far exceeds 16 workers
        with pytest.raises(AssertionError, match="oversubscribed"):
            p.audit_schedule(Y)

    def test_rejects_too_wide_gang(self):
        p = self._problem()
        p.nworkers = p.nworkers.copy()
        p.nworkers[3] = p.num_gpus + 1
        Y = np.zeros((p.num_jobs, p.future_rounds), dtype=np.int64)
        Y[3, 0] = 1
        with pytest.raises(AssertionError, match="wider than the cluster"):
            p.audit_schedule(Y)

    @pytest.mark.slow
    def test_stress_scale_schedule_is_feasible(self):
        """VERDICT r03 weak #5: the 1000x256x50 schedule Y itself —
        capacity, gang widths, double grants — not just its objective."""
        import bench
        from shockwave_tpu.solver.eg_jax import solve_eg_level

        p = bench.make_problem(
            num_jobs=1000, future_rounds=50, num_gpus=256, seed=0
        )
        Y = solve_eg_level(p)
        p.audit_schedule(Y)
        # The solve must actually use the cluster: at stress scale the
        # budget-constrained optimum saturates most of the window.
        used = float((Y * p.nworkers[:, None]).sum())
        budget = float(p.num_gpus * p.future_rounds)
        assert used > 0.9 * budget

    @pytest.mark.slow
    def test_stress_scale_relaxed_matches_level(self):
        """VERDICT r04 weak #6: the relaxed (PGD) path gets the same
        1000x256x50 audit as the production level backend — schedule
        feasibility plus objective parity. PR 8 closed the 1.97% PGD
        parity debt (CHANGES PR 3) with the restarted-PDHG polish
        solve_eg_jax now applies: the polish optimizes the exact
        nonsmooth objective from the PGD iterate, where PGD's
        smoothed-max makespan left its gap."""
        import bench
        from shockwave_tpu.solver.eg_jax import solve_eg_jax, solve_eg_level
        from shockwave_tpu.solver.rounding import schedule_from_relaxed

        p = bench.make_problem(
            num_jobs=1000, future_rounds=50, num_gpus=256, seed=0
        )
        s = solve_eg_jax(p)
        Y = schedule_from_relaxed(
            s,
            p.priorities,
            p.nworkers,
            p.num_gpus,
            p.future_rounds,
            problem=p,
        )
        p.audit_schedule(Y)
        o_relaxed = p.objective_value(Y)
        o_level = p.objective_value(solve_eg_level(p))
        assert o_relaxed >= o_level - 0.01 * abs(o_level)


class TestSwitchingCost:
    """The preemption-aware extended objective: dropping an incumbent
    (granting it zero rounds) charges its measured relaunch overhead,
    regularizer-scaled — the same currency as the makespan term. Every
    backend must optimize the SAME extended objective, and zero overhead
    must reproduce the historical plans bit-identically."""

    def switchy_problem(self, seed, J=4, R=3, num_gpus=3):
        import dataclasses

        rng = np.random.default_rng(seed)
        p = random_problem(rng, J=J, R=R, num_gpus=num_gpus)
        incumbent = (rng.random(J) < 0.5).astype(np.float64)
        if not incumbent.any():
            incumbent[int(rng.integers(J))] = 1.0
        # Costs sized so the bonus (regularizer 1e-4 x cost) lands in the
        # same decade as the welfare terms: the term must actually bind.
        switch_cost = rng.uniform(200.0, 3000.0, J) * incumbent
        return dataclasses.replace(
            p, switch_cost=switch_cost, incumbent=incumbent
        )

    def test_switch_bonus_and_objective_charge(self):
        """objective_value charges exactly regularizer * cost for every
        incumbent a schedule grants zero rounds, relative to the
        overhead-blind objective on the same schedule."""
        import dataclasses

        p = self.switchy_problem(0)
        bonus = p.switch_bonus()
        np.testing.assert_allclose(
            bonus, p.regularizer * p.switch_cost * p.incumbent
        )
        p_blind = dataclasses.replace(p, switch_cost=None, incumbent=None)
        j = int(np.argmax(bonus))
        Y_keep = np.zeros((p.num_jobs, p.future_rounds), dtype=int)
        Y_keep[j, 0] = 1
        Y_drop = np.zeros_like(Y_keep)
        total_bonus = float(np.sum(bonus))
        assert p.objective_value(Y_drop) == pytest.approx(
            p_blind.objective_value(Y_drop) - total_bonus
        )
        assert p.objective_value(Y_keep) == pytest.approx(
            p_blind.objective_value(Y_keep) - (total_bonus - bonus[j])
        )

    @pytest.mark.parametrize("seed", range(5))
    def test_milp_matches_brute_force_with_switch_cost(self, seed):
        p = self.switchy_problem(seed)
        best, _ = brute_force_best(p)
        Y = solve_eg_milp(p, rel_gap=1e-9, time_limit=30)
        assert np.all(p.nworkers @ Y <= p.num_gpus + 1e-9)
        assert p.objective_value(Y) == pytest.approx(best, abs=1e-6)

    @pytest.mark.parametrize("seed", range(5))
    def test_level_and_greedy_near_milp_with_switch_cost(self, seed):
        from shockwave_tpu.solver.eg_jax import solve_eg_level

        p = self.switchy_problem(100 + seed, J=6, R=4)
        Y_milp = solve_eg_milp(p, rel_gap=1e-6, time_limit=30)
        obj_milp = p.objective_value(Y_milp)
        scale = max(1.0, abs(obj_milp))
        for Y in (
            solve_eg_level(p),
            reorder_columns(solve_eg_greedy(p), p.priorities),
        ):
            assert np.all(p.nworkers @ Y <= p.num_gpus + 1e-9)
            assert p.objective_value(Y) >= obj_milp - 0.08 * scale

    @pytest.mark.parametrize("seed", range(5))
    def test_zero_overhead_reproduces_plans_bit_identically(self, seed):
        """switch_cost=0 (or incumbent empty) must leave every backend's
        plan EXACTLY as the historical overhead-blind formulation —
        including the jit cache path (pad_problem omits the bonus)."""
        import dataclasses

        from shockwave_tpu.solver.eg_jax import solve_eg_level

        rng = np.random.default_rng(200 + seed)
        p_blind = random_problem(rng, J=5, R=3)
        p_zero = dataclasses.replace(
            p_blind,
            switch_cost=np.zeros(p_blind.num_jobs),
            incumbent=np.ones(p_blind.num_jobs),
        )
        np.testing.assert_array_equal(
            solve_eg_level(p_blind), solve_eg_level(p_zero)
        )
        np.testing.assert_array_equal(
            solve_eg_greedy(p_blind), solve_eg_greedy(p_zero)
        )
        np.testing.assert_array_equal(
            solve_eg_milp(p_blind, rel_gap=1e-9, time_limit=30),
            solve_eg_milp(p_zero, rel_gap=1e-9, time_limit=30),
        )

    def test_large_overhead_keeps_incumbent_scheduled(self):
        """One slot, one round, two jobs: the challenger wins the
        overhead-blind program; a relaunch overhead larger than the
        utility gap flips the grant to the incumbent on every backend."""
        import dataclasses

        from shockwave_tpu.solver.eg_jax import solve_eg_level

        # Both jobs half done, so each marginal utility is a modest
        # log-slope step (a job at zero progress sits on the log(1e-6)
        # floor, whose ~12-nat first-grant marginal would dwarf any
        # realistic relaunch bonus).
        base = make_problem(
            priorities=[5.0, 1.0],
            completed=[2, 2],
            total=[4, 4],
            epoch_dur=[100.0, 100.0],
            remaining=[200.0, 200.0],
            nworkers=[1.0, 1.0],
            num_gpus=1,
            round_duration=100.0,
            future_rounds=1,
            regularizer=1e-3,
        )
        sticky = dataclasses.replace(
            base,
            switch_cost=np.array([0.0, 5000.0]),
            incumbent=np.array([0.0, 1.0]),
        )
        for solver in (
            lambda q: solve_eg_milp(q, rel_gap=1e-9, time_limit=30),
            solve_eg_level,
            solve_eg_greedy,
        ):
            Y_blind = np.asarray(solver(base))
            assert Y_blind[0].sum() == 1 and Y_blind[1].sum() == 0
            Y_sticky = np.asarray(solver(sticky))
            assert Y_sticky[1].sum() == 1, (
                "incumbent with dominant relaunch overhead was dropped"
            )
