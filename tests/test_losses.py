"""Loss-function unit tests (fast tier: no subprocesses, no models)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_token_xent_matches_naive_log_softmax():
    """The fused logsumexp/select-reduce cross entropy (rewritten for
    TPU: the take_along_axis gather's scatter backward cost 58 ms
    fwd+bwd at [16384, 8192] on a v5e) must match the naive
    log-softmax formulation exactly, values and gradients."""
    from shockwave_tpu.models.small_models import token_xent

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((4, 16, 32)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, 32, (4, 16)), jnp.int32)

    def naive(lg):
        logp = jax.nn.log_softmax(lg, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    v_new, g_new = jax.value_and_grad(lambda lg: token_xent(lg, targets))(
        logits
    )
    v_old, g_old = jax.value_and_grad(naive)(logits)
    assert float(v_new) == pytest.approx(float(v_old), rel=1e-6)
    np.testing.assert_allclose(
        np.asarray(g_new), np.asarray(g_old), rtol=1e-5, atol=1e-7
    )
