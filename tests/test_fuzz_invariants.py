"""Randomized simulator invariants: random traces under random
policies, checked via the structured round log — capacity never
exceeded, no job lost, every completed job ran all its steps, gang
widths respected. The property-level safety net behind the per-policy
golden and e2e tests."""

import re

import pytest

from shockwave_tpu.core.scheduler import Scheduler
from shockwave_tpu.data.default_oracle import generate_oracle
from shockwave_tpu.data.generate import generate_trace_jobs
from shockwave_tpu.data.profiles import synthesize_profiles
from shockwave_tpu.policies import get_policy

POLICIES = [
    "fifo",
    "max_min_fairness",
    "finish_time_fairness_perf",
    "gandiva",
    "shockwave_tpu",
]


@pytest.mark.parametrize("mode_mix", ["static", "dynamic"])
@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("policy_name", POLICIES)
def test_random_trace_invariants(policy_name, seed, mode_mix):
    from shockwave_tpu.data.generate import DYNAMIC_MODE_DIST

    oracle = generate_oracle()
    jobs, arrivals = generate_trace_jobs(
        num_jobs=10 + 3 * seed,
        throughputs=oracle,
        seed=seed,
        lam=120.0,
        **(
            {"mode_dist": DYNAMIC_MODE_DIST}
            if mode_mix == "dynamic"
            else {}
        ),
    )
    profiles = synthesize_profiles(jobs, oracle)
    for i, job in enumerate(jobs):
        job.duration = sum(profiles[i]["duration_every_epoch"])
    num_gpus = 6
    shockwave_config = (
        {
            "num_gpus": num_gpus,
            "time_per_iteration": 120,
            "future_rounds": 10,
            "lambda": 5.0,
            "k": 10.0,
        }
        if policy_name.startswith("shockwave")
        else None
    )
    sched = Scheduler(
        get_policy(policy_name, seed=seed),
        throughputs=oracle,
        seed=seed,
        time_per_iteration=120,
        profiles=profiles,
        shockwave_config=shockwave_config,
    )
    makespan = sched.simulate({"v100": num_gpus}, arrivals, jobs)
    assert makespan > 0

    # No job lost: every admitted job reaches a completion record.
    assert len(sched._job_completion_times) == len(jobs)
    for job_id, jct in sched._job_completion_times.items():
        assert jct is not None and jct > 0, job_id

    # Completed steps. Static jobs must have run EXACTLY-or-more their
    # total steps; dynamic (accordion/gns) jobs rescale total_steps
    # mid-run, so the invariant there is positive progress.
    scale = {i: j.scale_factor for i, j in enumerate(jobs)}
    steps_run = sched.get_completed_steps()
    for i, job in enumerate(jobs):
        steps = steps_run[i]
        if job.mode == "static":
            assert steps >= job.total_steps, (i, steps, job.total_steps)
        else:
            assert steps > 0, i

    # Capacity and gang width, via the round log: never over capacity,
    # and a scheduled gang occupies exactly scale_factor workers.
    for ev in sched._round_log:
        if ev["event"] != "round":
            continue
        assert sum(ev["jobs"].values()) <= num_gpus, ev
        for key, width in ev["jobs"].items():
            assert width >= 1, ev
            ids = [int(tok) for tok in re.findall(r"\d+", key)]
            if len(ids) == 1 and ids[0] in scale:
                assert width == scale[ids[0]], (key, width)
