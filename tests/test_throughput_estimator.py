"""Throughput-estimator tests (reference test style:
scheduler/tests/throughput_estimation_tests.py): identity when fully
profiled; confined to reference types when sampled; ALS completion
accuracy on synthetic low-rank data."""

import numpy as np
import pytest

from shockwave_tpu.core.throughput_estimator import ThroughputEstimator
from shockwave_tpu.data.default_oracle import generate_oracle
from shockwave_tpu.ops.matrix_completion import complete, masked_als


def oracle_and_types():
    oracle = generate_oracle()
    # Single-GPU job types that have colocated entries against each other.
    job_types = [
        key
        for key in sorted(oracle["v100"].keys())
        if key[1] == 1
    ][:8]
    trimmed = {}
    for wt in ["v100", "p100", "k80"]:
        trimmed[wt] = {}
        for jt in job_types:
            entry = {"null": oracle[wt][jt]["null"]}
            for other in job_types:
                entry[other] = oracle[wt][jt][other]
            trimmed[wt][jt] = entry
    return trimmed, job_types


class TestEstimator:
    def test_fully_profiled_identity(self):
        oracle, job_types = oracle_and_types()
        est = ThroughputEstimator(
            oracle,
            ["k80", "p100", "v100"],
            job_types,
            num_reference_job_types=len(job_types),
            profiling_percentage=1.0,
            seed=0,
        )
        for jt in job_types:
            assert est.match_job_to_reference_job(jt) == jt

    def test_sampled_profiling_returns_reference_type(self):
        oracle, job_types = oracle_and_types()
        est = ThroughputEstimator(
            oracle,
            ["k80", "p100", "v100"],
            job_types,
            num_reference_job_types=4,
            profiling_percentage=0.5,
            seed=1,
        )
        for jt in job_types:
            match = est.match_job_to_reference_job(jt)
            assert match in est._reference_job_types

    def test_reference_throughputs_shape(self):
        oracle, job_types = oracle_and_types()
        est = ThroughputEstimator(
            oracle,
            ["k80", "p100", "v100"],
            job_types,
            num_reference_job_types=4,
            profiling_percentage=0.5,
        )
        ref = est.get_reference_throughputs()
        assert set(ref.keys()) == {"k80", "p100", "v100"}
        for wt in ref:
            assert len(ref[wt]) == 4
            for jt in ref[wt]:
                for other in ref[wt][jt]:
                    assert len(ref[wt][jt][other]) == 2


class TestMaskedALS:
    def test_recovers_low_rank_matrix(self):
        rng = np.random.default_rng(0)
        U = rng.uniform(0.2, 1.0, (12, 3))
        V = rng.uniform(0.2, 1.0, (15, 3))
        X = (U @ V.T) / 3.0  # keep entries in [0, 1]
        mask = (rng.uniform(size=X.shape) < 0.7).astype(float)
        est = complete(X * mask, mask, k=3)
        err = np.abs(est - X)[mask == 0]
        assert err.mean() < 0.08

    def test_observed_entries_preserved(self):
        rng = np.random.default_rng(1)
        X = rng.uniform(0, 1, (6, 6))
        mask = np.ones_like(X)
        mask[2, 3] = 0
        out = complete(X * mask, mask, k=3)
        np.testing.assert_array_equal(out[mask == 1], X[mask == 1])

    def test_jit_shape_stability(self):
        import jax.numpy as jnp

        X = jnp.ones((4, 4))
        mask = jnp.ones((4, 4))
        out = masked_als(X, mask, k=2)
        assert out.shape == (4, 4)


class TestSchedulerIntegration:
    """The estimator wired into the scheduler (reference:
    scheduler.py:286-292,573-575,2531-2555): packing policies see
    estimated pair throughputs while execution uses the oracle truth."""

    def _run(self, **sched_kwargs):
        from shockwave_tpu.core.scheduler import Scheduler
        from shockwave_tpu.data.default_oracle import generate_oracle
        from shockwave_tpu.data.profiles import synthesize_profiles
        from shockwave_tpu.data.workload_info import steps_per_epoch
        from shockwave_tpu.core.job import Job
        from shockwave_tpu.policies import get_policy

        oracle = generate_oracle()
        types = [
            ("ResNet-18", 32), ("LM", 10), ("Transformer", 16),
            ("ResNet-50", 16), ("Recommendation", 1024), ("ResNet-18", 128),
        ]
        jobs = [
            Job(
                job_type=f"{fam} (batch size {bs})",
                total_steps=steps_per_epoch(fam, bs) * 2,
                scale_factor=1,
                mode="static",
            )
            for fam, bs in types
        ]
        sched = Scheduler(
            get_policy("max_min_fairness_packed"),
            throughputs=oracle,
            seed=0,
            time_per_iteration=120,
            profiles=synthesize_profiles(jobs, oracle),
            **sched_kwargs,
        )
        makespan = sched.simulate({"v100": 2}, [0.0] * len(jobs), jobs)
        return sched, makespan, oracle

    def test_estimation_mode_completes_and_matches(self):
        sched, makespan, oracle = self._run(
            profiling_percentage=0.5, num_reference_models=12
        )
        assert sched._estimate_throughputs
        # Every (scale-factor-1) job was matched to a reference type.
        assert len(sched._reference_job_map) == 6
        for ref in sched._reference_job_map.values():
            assert ref in {
                t for wt in sched._reference_throughputs.values() for t in wt
            }
        # The trace still completes with the oracle hidden from the policy.
        assert len(sched._job_completion_times) == 6
        assert all(
            t is not None for t in sched._job_completion_times.values()
        )
        assert makespan > 0

    def test_estimates_converge_to_truth_once_pairs_run(self):
        sched, _, oracle = self._run(
            profiling_percentage=0.5, num_reference_models=12
        )
        # _update_throughput replaced estimates of executed pairs with the
        # oracle truth; any remaining pair entries are estimates (positive,
        # bounded by isolated throughput).
        pair_ids = [j for j in sched._throughputs if j.is_pair]
        for pair in pair_ids:
            for wt, tputs in sched._throughputs[pair].items():
                assert len(tputs) == 2
                assert all(t >= 0 for t in tputs)

    def test_full_profiling_is_off_by_default(self):
        sched, _, _ = self._run()
        assert not sched._estimate_throughputs
