"""Throughput-estimator tests (reference test style:
scheduler/tests/throughput_estimation_tests.py): identity when fully
profiled; confined to reference types when sampled; ALS completion
accuracy on synthetic low-rank data."""

import numpy as np
import pytest

from shockwave_tpu.core.throughput_estimator import ThroughputEstimator
from shockwave_tpu.data.default_oracle import generate_oracle
from shockwave_tpu.ops.matrix_completion import complete, masked_als


def oracle_and_types():
    oracle = generate_oracle()
    # Single-GPU job types that have colocated entries against each other.
    job_types = [
        key
        for key in sorted(oracle["v100"].keys())
        if key[1] == 1
    ][:8]
    trimmed = {}
    for wt in ["v100", "p100", "k80"]:
        trimmed[wt] = {}
        for jt in job_types:
            entry = {"null": oracle[wt][jt]["null"]}
            for other in job_types:
                entry[other] = oracle[wt][jt][other]
            trimmed[wt][jt] = entry
    return trimmed, job_types


class TestEstimator:
    def test_fully_profiled_identity(self):
        oracle, job_types = oracle_and_types()
        est = ThroughputEstimator(
            oracle,
            ["k80", "p100", "v100"],
            job_types,
            num_reference_job_types=len(job_types),
            profiling_percentage=1.0,
            seed=0,
        )
        for jt in job_types:
            assert est.match_job_to_reference_job(jt) == jt

    def test_sampled_profiling_returns_reference_type(self):
        oracle, job_types = oracle_and_types()
        est = ThroughputEstimator(
            oracle,
            ["k80", "p100", "v100"],
            job_types,
            num_reference_job_types=4,
            profiling_percentage=0.5,
            seed=1,
        )
        for jt in job_types:
            match = est.match_job_to_reference_job(jt)
            assert match in est._reference_job_types

    def test_reference_throughputs_shape(self):
        oracle, job_types = oracle_and_types()
        est = ThroughputEstimator(
            oracle,
            ["k80", "p100", "v100"],
            job_types,
            num_reference_job_types=4,
            profiling_percentage=0.5,
        )
        ref = est.get_reference_throughputs()
        assert set(ref.keys()) == {"k80", "p100", "v100"}
        for wt in ref:
            assert len(ref[wt]) == 4
            for jt in ref[wt]:
                for other in ref[wt][jt]:
                    assert len(ref[wt][jt][other]) == 2


class TestMaskedALS:
    def test_recovers_low_rank_matrix(self):
        rng = np.random.default_rng(0)
        U = rng.uniform(0.2, 1.0, (12, 3))
        V = rng.uniform(0.2, 1.0, (15, 3))
        X = (U @ V.T) / 3.0  # keep entries in [0, 1]
        mask = (rng.uniform(size=X.shape) < 0.7).astype(float)
        est = complete(X * mask, mask, k=3)
        err = np.abs(est - X)[mask == 0]
        assert err.mean() < 0.08

    def test_observed_entries_preserved(self):
        rng = np.random.default_rng(1)
        X = rng.uniform(0, 1, (6, 6))
        mask = np.ones_like(X)
        mask[2, 3] = 0
        out = complete(X * mask, mask, k=3)
        np.testing.assert_array_equal(out[mask == 1], X[mask == 1])

    def test_jit_shape_stability(self):
        import jax.numpy as jnp

        X = jnp.ones((4, 4))
        mask = jnp.ones((4, 4))
        out = masked_als(X, mask, k=2)
        assert out.shape == (4, 4)
