"""Fleet observability tests: causal trace-context propagation, the
NTP-style clock estimator, the fleet metrics plane (worker-label merge
+ scrape endpoint), the span-tree merge/latency-budget math, the
clock_skew watchdog rule, the shared bucket-quantile helper, and the
worker agent's SIGTERM telemetry flush."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from shockwave_tpu import obs
from shockwave_tpu.obs import propagate, spantree
from shockwave_tpu.obs.fleet import (
    ClockEstimator,
    FleetTelemetry,
    merge_prometheus_texts,
    relabel_prometheus_text,
)
from shockwave_tpu.obs.metrics import quantile_from_buckets

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def clean_obs():
    obs.reset()
    propagate.configure_sampling(None)
    yield
    obs.reset()
    propagate.configure_sampling(None)


# ----------------------------------------------------------------------
# Trace-context propagation.
# ----------------------------------------------------------------------
class TestPropagate:
    def test_disabled_tracing_short_circuits(self):
        assert propagate.new_root() is None
        assert propagate.ctx_args(None) == {}
        assert propagate.ctx_wire(None) == ""

    def test_root_child_and_wire_roundtrip(self):
        obs.configure(trace=True)
        root = propagate.new_root()
        assert root is not None and root.sampled
        child = root.child()
        assert child.trace_id == root.trace_id
        assert child.parent_span_id == root.span_id
        assert child.span_id != root.span_id
        parsed = propagate.from_wire(child.to_wire())
        assert parsed.trace_id == root.trace_id
        assert parsed.span_id == child.span_id
        assert parsed.sampled

    def test_from_wire_tolerates_garbage(self):
        assert propagate.from_wire("") is None
        assert propagate.from_wire("not-a-context") is None
        assert propagate.from_wire("zz-yy-1") is None
        assert propagate.from_wire("abc") is None

    def test_args_shape(self):
        obs.configure(trace=True)
        root = propagate.new_root()
        args = root.args()
        assert args == {
            "trace_id": root.trace_id, "span_id": root.span_id
        }
        child_args = root.child().args()
        assert child_args["parent_span_id"] == root.span_id

    def test_unsampled_context_ships_nothing(self):
        ctx = propagate.TraceContext("aa", "bb", sampled=False)
        assert ctx.to_wire() == ""
        assert ctx.child().sampled is False

    def test_deterministic_sampling_fraction(self):
        obs.configure(trace=True)
        propagate.configure_sampling(0.5)
        decisions = [propagate.new_root().sampled for _ in range(6)]
        assert decisions == [True, False, True, False, True, False]
        propagate.configure_sampling(0.0)
        assert propagate.new_root().sampled is False
        propagate.configure_sampling(1.0)
        assert propagate.new_root().sampled is True

    def test_force_sample_skips_the_counter(self):
        obs.configure(trace=True)
        propagate.configure_sampling(0.5)
        first = propagate.new_root()          # counter 0 -> sampled
        forced = propagate.new_root(force_sample=True)
        second = propagate.new_root()         # counter 1 -> unsampled
        third = propagate.new_root()          # counter 2 -> sampled
        assert first.sampled and forced.sampled
        assert not second.sampled and third.sampled

    def test_adopt_or_root_prefers_wire(self):
        obs.configure(trace=True)
        root = propagate.new_root()
        adopted = propagate.adopt_or_root(root.to_wire())
        assert adopted.trace_id == root.trace_id
        fresh = propagate.adopt_or_root("")
        assert fresh is not None and fresh.trace_id != root.trace_id


# ----------------------------------------------------------------------
# quantile_from_buckets (the factored p99 math).
# ----------------------------------------------------------------------
class TestQuantileFromBuckets:
    def test_empty(self):
        assert quantile_from_buckets({}, 0.99) == (None, 0)
        assert quantile_from_buckets({"+Inf": 0}, 0.99) == (None, 0)

    def test_single_bucket(self):
        value, count = quantile_from_buckets(
            {"1.0": 5, "+Inf": 5}, 0.99
        )
        assert value == 1.0 and count == 5

    def test_inf_only_resolves_to_observed_max(self):
        value, count = quantile_from_buckets(
            {"+Inf": 7}, 0.99, observed_max=41.5
        )
        assert value == 41.5 and count == 7
        value, _ = quantile_from_buckets({"+Inf": 7}, 0.99)
        assert value is None

    def test_typical_distribution(self):
        buckets = {"0.1": 90, "1.0": 98, "10.0": 100, "+Inf": 100}
        assert quantile_from_buckets(buckets, 0.5)[0] == 0.1
        assert quantile_from_buckets(buckets, 0.99)[0] == 10.0
        assert quantile_from_buckets(buckets, 0.95)[0] == 1.0

    def test_watchdog_reads_sketch_not_bucket_interpolation(self):
        """Since PR 19 the watchdog's quantile rules read the merged
        quantile SKETCH (alpha relative error), not the bucket-table
        upper bound: p99 of {0.02 x4, 40.0} is ~40.0, where the old
        interpolation answered 60.0 (the next le boundary)."""
        from shockwave_tpu.obs.watchdog import Watchdog

        obs.configure(metrics=True)
        h = obs.get_registry().histogram("q_test")
        for v in (0.02, 0.02, 0.02, 0.02, 40.0):
            h.observe(v)
        metrics = obs.get_registry().snapshot()["metrics"]
        value, count = Watchdog._histogram_quantile(
            metrics, "q_test", 0.99
        )
        assert count == 5
        assert abs(value - 40.0) / 40.0 <= 0.01
        # The bucket fallback (pre-sketch dumps) still answers the old
        # upper bound through quantile_from_buckets.
        series = metrics["q_test"]["series"][0]
        assert quantile_from_buckets(
            series["buckets"], 0.99, series["max"]
        ) == (60.0, 5)
        # Stripping the sketches reproduces the fallback path.
        for s in metrics["q_test"]["series"]:
            s.pop("sketch", None)
        fallback, _ = Watchdog._histogram_quantile(metrics, "q_test", 0.99)
        assert fallback == 60.0


# ----------------------------------------------------------------------
# Clock estimation.
# ----------------------------------------------------------------------
def test_gauge_series_removal():
    obs.configure(metrics=True)
    gauge = obs.gauge("worker_clock_offset_seconds", "offset")
    gauge.set(0.5, worker="3")
    gauge.set(0.7, worker="5")
    gauge.remove(worker="3")
    gauge.remove(worker="99")  # absent series: no-op
    snap = obs.get_registry().snapshot()["metrics"]
    workers = [
        s["labels"]["worker"]
        for s in snap["worker_clock_offset_seconds"]["series"]
    ]
    assert workers == ["5"]


def test_negative_varint_encodes_like_protoc():
    from shockwave_tpu.runtime.protobuf.wire import (
        decode_varint,
        encode_varint,
    )

    encoded = encode_varint(-1)
    assert len(encoded) == 10  # two's-complement 64-bit, protoc-style
    value, pos = decode_varint(encoded, 0)
    assert value == 0xFFFFFFFFFFFFFFFF and pos == 10


class TestClockEstimator:
    def test_min_rtt_sample_wins(self):
        clock = ClockEstimator()
        clock.add((0.5, 0.10))
        clock.add((0.1, 0.01))  # tightest round trip
        clock.add((0.9, 0.50))
        assert clock.best() == (0.1, 0.01)
        assert clock.offset() == 0.1

    def test_none_and_invalid_ignored(self):
        clock = ClockEstimator()
        clock.add(None)
        clock.add((1.0, 0.0))
        clock.add((1.0, -1.0))
        assert clock.best() is None and clock.offset() is None

    def test_window_forgets_stale_best(self):
        clock = ClockEstimator(window=2)
        clock.add((0.1, 0.01))
        clock.add((0.2, 0.05))
        clock.add((0.3, 0.07))  # evicts the 0.01-rtt sample
        assert clock.best() == (0.2, 0.05)

    def test_ntp_sample_math(self):
        from shockwave_tpu.runtime.rpc.worker_client import _clock_sample

        # Worker clock 10 s behind scheduler, symmetric 0.1 s legs.
        t0, t1, t2, t3 = 100.0, 110.1, 110.2, 100.3
        offset, rtt = _clock_sample(t0, t1, t2, t3)
        assert offset == pytest.approx(10.0)
        assert rtt == pytest.approx(0.2)
        assert _clock_sample(t0, 0.0, 0.0, t3) is None


# ----------------------------------------------------------------------
# Prometheus text merging.
# ----------------------------------------------------------------------
class TestPrometheusMerge:
    def test_relabel_injects_worker_label(self):
        text = (
            "# HELP c jobs\n# TYPE c counter\n"
            'c{kind="x"} 3\nc 1\n'
        )
        out = relabel_prometheus_text(text, worker="2")
        assert 'c{kind="x",worker="2"} 3' in out
        assert 'c{worker="2"} 1' in out
        assert "# TYPE c counter" in out

    def test_merge_dedupes_headers_and_keeps_samples(self):
        sched = "# HELP c jobs\n# TYPE c counter\nc 1\n"
        worker = '# HELP c jobs\n# TYPE c counter\nc{worker="2"} 3\n'
        merged = merge_prometheus_texts([sched, worker])
        assert merged.count("# TYPE c counter") == 1
        assert "c 1" in merged and 'c{worker="2"} 3' in merged

    def test_histogram_children_stay_with_family(self):
        text = (
            "# TYPE h histogram\n"
            'h_bucket{le="1.0"} 1\nh_bucket{le="+Inf"} 2\n'
            "h_sum 1.5\nh_count 2\n"
            "# TYPE h_min gauge\nh_min 0.5\n"
        )
        merged = merge_prometheus_texts([text])
        lines = merged.splitlines()
        assert lines.index("# TYPE h histogram") < lines.index("h_sum 1.5")
        assert "# TYPE h_min gauge" in merged


# ----------------------------------------------------------------------
# FleetTelemetry: pull, merge, endpoints.
# ----------------------------------------------------------------------
class TestFleetTelemetry:
    def test_poll_merge_and_render(self):
        obs.configure(metrics=True)
        obs.counter("sched_only_total", "scheduler series").inc()
        fleet = FleetTelemetry(scrape_interval_s=30)
        fleet.add_target(
            "3",
            lambda: "# TYPE worker_launches_total counter\n"
            "worker_launches_total 5\n",
        )
        fleet.add_target(
            "7",
            lambda: "# TYPE worker_launches_total counter\n"
            "worker_launches_total 2\n",
        )
        assert fleet.poll_once() == 2
        text = fleet.render()
        assert "sched_only_total 1" in text
        assert 'worker_launches_total{worker="3"} 5' in text
        assert 'worker_launches_total{worker="7"} 2' in text
        assert text.count("# TYPE worker_launches_total counter") == 1

    def test_failed_target_counted_not_fatal(self):
        obs.configure(metrics=True)

        def boom():
            raise ConnectionError("worker gone")

        fleet = FleetTelemetry(scrape_interval_s=30)
        fleet.add_target("3", boom)
        assert fleet.poll_once() == 0
        snap = obs.get_registry().snapshot()["metrics"]
        assert "fleet_scrape_failures_total" in snap

    def test_remove_target_drops_dump(self):
        fleet = FleetTelemetry(scrape_interval_s=30)
        fleet.add_target("3", lambda: "x_total 1\n")
        fleet.poll_once()
        fleet.remove_target("3")
        assert 'worker="3"' not in fleet.render()

    def test_http_endpoints(self):
        obs.configure(metrics=True)
        obs.counter("sched_only_total", "scheduler series").inc()
        fleet = FleetTelemetry(scrape_interval_s=30)
        fleet.add_target("0", lambda: "w_total 1\n")
        fleet.poll_once()
        fleet.start(http_port=0)
        try:
            base = f"http://127.0.0.1:{fleet.port}"
            with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
                body = r.read().decode()
                assert r.status == 200
                assert 'w_total{worker="0"} 1' in body
                assert "sched_only_total 1" in body
            with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
                health = json.loads(r.read().decode())
                assert r.status == 200
                assert health["status"] == "ok"
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(base + "/nope", timeout=5)
            assert err.value.code == 404
        finally:
            fleet.stop()

    def test_healthz_degraded_when_watchdog_gauge_zero(self):
        obs.configure_watchdog()
        obs.gauge("scheduler_health", "health").set(0.0)
        obs.get_watchdog().alerts.append(
            {"rule": "worst_ftf", "round": 1, "value": 9.0,
             "threshold": 2.0, "time_s": 0.0}
        )
        fleet = FleetTelemetry(scrape_interval_s=30)
        code, body = fleet.healthz()
        assert code == 503 and body["status"] == "degraded"
        assert body["watchdog"]["alerts"] == 1


# ----------------------------------------------------------------------
# clock_skew watchdog rule.
# ----------------------------------------------------------------------
class TestClockSkewRule:
    def _offset(self, worker, value):
        obs.gauge(
            "worker_clock_offset_seconds", "offset"
        ).set(value, worker=worker)

    def test_fires_on_offset_past_threshold_once_per_episode(self):
        obs.configure_watchdog()
        watchdog = obs.get_watchdog()
        self._offset("3", 2.5)
        fired = watchdog.check_round(1, 1.0)
        assert [a["rule"] for a in fired] == ["clock_skew"]
        assert fired[0]["worker"] == "3"
        # Persisting breach: no per-round spam.
        assert watchdog.check_round(2, 2.0) == []
        # Recovery re-arms; a new breach fires again.
        self._offset("3", 0.0)
        assert watchdog.check_round(3, 3.0) == []
        self._offset("3", 3.0)
        refired = watchdog.check_round(4, 4.0)
        assert [a["rule"] for a in refired] == ["clock_skew"]

    def test_fires_on_jump_between_heartbeats(self):
        obs.configure_watchdog(
            {"clock_skew": {"max_offset_s": 10.0, "max_jump_s": 0.2}}
        )
        watchdog = obs.get_watchdog()
        self._offset("3", 0.1)
        assert watchdog.check_round(1, 1.0) == []
        self._offset("3", 0.9)  # |jump| = 0.8 > 0.2, offset under max
        fired = watchdog.check_round(2, 2.0)
        assert [a["rule"] for a in fired] == ["clock_skew"]
        assert fired[0]["jump_s"] == pytest.approx(0.8)

    def test_per_worker_isolation(self):
        obs.configure_watchdog()
        watchdog = obs.get_watchdog()
        self._offset("3", 2.5)
        self._offset("5", 0.0)
        fired = watchdog.check_round(1, 1.0)
        assert len(fired) == 1
        # A second worker breaching is NOT masked by the first.
        self._offset("5", -4.0)
        fired = watchdog.check_round(2, 2.0)
        assert [a["worker"] for a in fired] == ["5"]


# ----------------------------------------------------------------------
# Span-tree math.
# ----------------------------------------------------------------------
def _span(name, ts_s, dur_s, pid, ctx=None, **args):
    e = {
        "name": name, "ph": "X", "pid": pid, "tid": 1,
        "ts": ts_s * 1e6, "dur": dur_s * 1e6,
        "args": dict(args),
    }
    if ctx is not None:
        e["args"].update(ctx.args())
    return e


def _instant(name, ts_s, pid, **args):
    return {
        "name": name, "ph": "i", "pid": pid, "tid": 1,
        "ts": ts_s * 1e6, "args": dict(args),
    }


class TestSpanTree:
    def _chain_events(self):
        root = propagate.TraceContext("t1", "r1")
        dispatch = root.child()
        run = dispatch.child()
        events = [
            _instant("job_submit", 0.0, 1, trace_id="t1", span_id="r1",
                     job_type="x"),
            _instant("job_admitted", 1.0, 1, job_id=4, arrival_s=0.0,
                     trace_id="t1", parent_span_id="r1"),
            _span("queue_wait", 0.0, 1.0, 1, ctx=root.child(), job_id=4),
            _span("solve:pdhg", 1.2, 0.5, 1),
            _span("dispatch", 2.0, 0.1, 1, ctx=dispatch, job_id="4"),
            _span("run_job", 2.2, 3.0, 2, ctx=run, job_id=4),
            _instant("job_complete", 5.5, 1, job_id=4,
                     trace_id="t1", parent_span_id="r1"),
        ]
        return events

    def test_collect_and_connectivity(self):
        chains = spantree.collect_chains(self._chain_events())
        assert set(chains) == {"t1"}
        summary = spantree.chain_summary(chains["t1"])
        assert summary["connected"]
        assert summary["processes"] == 2

    def test_broken_chain_detected(self):
        events = self._chain_events()
        # Orphan the run span: its parent is no known node.
        events[-2]["args"]["parent_span_id"] = "doesnotexist"
        chains = spantree.collect_chains(events)
        assert not spantree.chain_summary(chains["t1"])["connected"]

    def test_latency_budget_segments(self):
        budgets = spantree.latency_budget(self._chain_events())
        assert set(budgets) == {"4"}
        b = budgets["4"]
        assert b["queue_wait_s"] == pytest.approx(1.0)
        # solve overlaps [admitted=1.0, first_dispatch=2.0] for 0.5 s.
        assert b["plan_exposed_s"] == pytest.approx(0.5)
        assert b["dispatch_s"] == pytest.approx(0.1)
        assert b["run_s"] == pytest.approx(3.0)
        assert b["sync_s"] == pytest.approx(0.3)
        assert b["total_s"] == pytest.approx(5.5)
        fleet = spantree.budget_fleet_summary(budgets)
        assert fleet["jobs"] == 1
        assert fleet["mean_run_s"] == pytest.approx(3.0)
        assert spantree.budget_fleet_summary({}) is None

    def test_merge_aligns_clocks_and_draws_flows(self):
        root = propagate.TraceContext("t1", "r1")
        child = root.child()
        sched_trace = {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                 "args": {"name": "scheduler"}},
                _span("dispatch", 10.0, 0.1, 1, ctx=root),
            ],
            "otherData": {
                "role": "scheduler",
                "clock": {"wall_at_zero_s": 1000.0,
                          "offset_to_scheduler_s": 0.0},
            },
        }
        worker_trace = {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "pid": 1, "tid": 0,
                 "args": {"name": "worker"}},
                # Worker clock zero = wall 1007, and its wall clock runs
                # 3 s behind the scheduler's (offset +3): an event at
                # worker-trace 5 s is scheduler time 5 + (1007+3-1000).
                _span("run_job", 5.0, 1.0, 1, ctx=child),
            ],
            "otherData": {
                "role": "worker", "worker": "2",
                "clock": {"wall_at_zero_s": 1007.0,
                          "offset_to_scheduler_s": 3.0},
            },
        }
        merged = spantree.merge_traces([sched_trace, worker_trace])
        events = merged["traceEvents"]
        run = next(e for e in events if e["name"] == "run_job")
        assert run["ts"] == pytest.approx(15.0 * 1e6)
        # Worker pid remapped away from the scheduler's.
        dispatch = next(e for e in events if e["name"] == "dispatch")
        assert run["pid"] != dispatch["pid"]
        # One cross-process causal edge -> one s/f flow pair.
        assert merged["otherData"]["flow_edges"] == 1
        flow_phases = sorted(
            e["ph"] for e in events if e.get("cat") == "causal"
        )
        assert flow_phases == ["f", "s"]
        # Worker process name carries its identity suffix.
        names = [
            e["args"]["name"] for e in events
            if e.get("ph") == "M" and e["name"] == "process_name"
        ]
        assert any("worker 2" in n for n in names)

    def test_packed_pair_spans_credit_both_members(self):
        assert spantree._job_keys("(3, 7)") == ["3", "7"]
        assert spantree._job_keys(4) == ["4"]
        events = [
            _instant("job_admitted", 1.0, 1, job_id=3, arrival_s=0.0),
            _instant("job_admitted", 1.0, 1, job_id=7, arrival_s=0.0),
            _span("dispatch", 2.0, 0.1, 1, job_id="(3, 7)"),
            _span("run job (3, 7)", 2.1, 3.0, 1),
            _instant("job_complete", 5.1, 1, job_id=3),
            _instant("job_complete", 5.1, 1, job_id=7),
        ]
        budgets = spantree.latency_budget(events)
        for job in ("3", "7"):
            assert budgets[job]["dispatch_s"] == pytest.approx(0.1)
            assert budgets[job]["run_s"] == pytest.approx(3.0)

    def test_packed_sim_run_span_with_first_members_context(self):
        # A sim pair run span only carries the FIRST member's chain in
        # its trace args; the name is authoritative so BOTH members
        # must still be credited.
        root3 = propagate.TraceContext("t3", "r3")
        events = [
            _instant("job_admitted", 1.0, 1, job_id=3, arrival_s=0.0,
                     trace_id="t3", parent_span_id="r3"),
            _instant("job_admitted", 1.0, 1, job_id=7, arrival_s=0.0),
            _span("run job (3, 7)", 2.0, 3.0, 1, ctx=root3.child()),
            _instant("job_complete", 5.0, 1, job_id=3),
            _instant("job_complete", 5.0, 1, job_id=7),
        ]
        budgets = spantree.latency_budget(events)
        assert budgets["3"]["run_s"] == pytest.approx(3.0)
        assert budgets["7"]["run_s"] == pytest.approx(3.0)

    def test_merge_reference_detection_and_errors(self):
        with pytest.raises(ValueError):
            spantree.merge_traces([])
        # Scheduler file not first: still chosen as reference.
        a = {"traceEvents": [], "otherData": {"role": "worker",
             "clock": {"wall_at_zero_s": 5.0}}}
        b = {"traceEvents": [], "otherData": {"role": "scheduler",
             "clock": {"wall_at_zero_s": 9.0}}}
        merged = spantree.merge_traces([a, b])
        sources = merged["otherData"]["sources"]
        assert sources[1]["reference"] is True
        assert sources[0]["shift_s"] == pytest.approx(-4.0)


# ----------------------------------------------------------------------
# End-to-end: a traced sim run produces connected chains (single
# process), and the tracer's clock metadata survives export.
# ----------------------------------------------------------------------
def test_sim_trace_chains_connected():
    from shockwave_tpu.core.scheduler import Scheduler
    from shockwave_tpu.data.default_oracle import generate_oracle
    from shockwave_tpu.data.generate import smoke_trace_jobs
    from shockwave_tpu.data.profiles import synthesize_profiles
    from shockwave_tpu.policies import get_policy

    obs.configure(metrics=True, trace=True)
    oracle = generate_oracle()
    jobs, arrivals = smoke_trace_jobs(4, epochs=1, arrival_gap_s=60.0)
    profiles = synthesize_profiles(jobs, oracle)
    sched = Scheduler(
        get_policy("fifo"),
        throughputs=oracle,
        seed=0,
        time_per_iteration=120,
        profiles=profiles,
    )
    sched.simulate({"v100": 2}, arrivals, jobs)
    events = obs.get_tracer().export_dict()["traceEvents"]
    chains = spantree.collect_chains(events)
    assert len(chains) == 4
    for chain in chains.values():
        assert spantree.chain_summary(chain)["connected"]
    budgets = spantree.latency_budget(events)
    assert len(budgets) == 4
    for budget in budgets.values():
        assert budget["total_s"] > 0


def test_tracer_export_carries_clock_meta():
    obs.configure(trace=True)
    tracer = obs.get_tracer()
    tracer.set_meta({"role": "worker", "clock": {
        "offset_to_scheduler_s": 1.5}})
    dump = tracer.export_dict()
    clock = dump["otherData"]["clock"]
    assert clock["offset_to_scheduler_s"] == 1.5
    assert clock["wall_at_zero_s"] > 0  # default anchor preserved
    assert dump["otherData"]["role"] == "worker"


# ----------------------------------------------------------------------
# Worker agent SIGTERM flush: a reclaimed agent must not lose its
# telemetry exports.
# ----------------------------------------------------------------------
def test_worker_agent_sigterm_flushes_telemetry(tmp_path):
    from shockwave_tpu.core.physical import PhysicalScheduler
    from shockwave_tpu.data.default_oracle import generate_oracle
    from shockwave_tpu.policies import get_policy
    from shockwave_tpu.utils.hostenv import free_port

    sched_port = free_port()
    sched = PhysicalScheduler(
        get_policy("fifo"),
        port=sched_port,
        throughputs=generate_oracle(),
        time_per_iteration=3.0,
        minimum_time_between_allocation_resets=0.0,
    )
    metrics_path = tmp_path / "worker_metrics.json"
    trace_path = tmp_path / "worker_trace.json"
    env = dict(os.environ)
    env.update(
        {
            "SHOCKWAVE_METRICS_OUT": str(metrics_path),
            "SHOCKWAVE_TRACE_OUT": str(trace_path),
            "SHOCKWAVE_HEARTBEAT_S": "0.2",
            "JAX_PLATFORMS": "cpu",
        }
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "shockwave_tpu.runtime.worker",
            "-t", "v100", "-n", "1",
            "-a", "127.0.0.1", "-s", str(sched_port),
            "-p", str(free_port()),
            "--run_dir", str(tmp_path / "run"),
            "--checkpoint_dir", str(tmp_path / "ckpt"),
        ],
        env=env,
        cwd=REPO_ROOT,
    )
    try:
        sched.wait_for_workers(1, timeout=60)
        # A couple of heartbeats so the agent has clock samples to
        # stamp into the export.
        time.sleep(1.0)
        assert not metrics_path.exists()  # nothing flushed yet
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=20)
        assert metrics_path.exists(), "SIGTERM lost the metrics export"
        assert trace_path.exists(), "SIGTERM lost the trace export"
        metrics = json.loads(metrics_path.read_text())
        assert metrics["schema"] == "shockwave-metrics-v1"
        trace = json.loads(trace_path.read_text())
        assert isinstance(trace["traceEvents"], list)
        assert trace["otherData"]["role"] == "worker"
        clock = trace["otherData"]["clock"]
        assert "offset_to_scheduler_s" in clock
    finally:
        if proc.poll() is None:
            proc.kill()
        sched.shutdown()
