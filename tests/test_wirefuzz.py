"""Differential wire fuzzer: seed determinism, a clean run over the
real codecs (byte-identity vs the dynamic protoc mirror + legacy
goldens, unknown-field/truncation tolerance, columnar round-trips),
descriptor conformance, proof that the differential actually DETECTS
drift (a mutated schema must produce failures), and the
scripts/ci/wire_smoke.py gate contract.
"""

import os
import subprocess
import sys

import pytest

from shockwave_tpu.analysis import repo_root
from shockwave_tpu.analysis.protospec import ProtoSchema, load_repo_schema
from shockwave_tpu.analysis.wirefuzz import (
    HANDROLLED_MODULES,
    LEGACY_MODULES,
    _finish_digests,
    build_protoc_mirror,
    codec_index,
    descriptor_conformance_problems,
    fuzz_schema,
)


def digests(report):
    return {
        name: fam["digest"]
        for name, fam in _finish_digests(report)["families"].items()
    }


class TestDeterminism:
    def test_same_seed_same_digests(self):
        a = digests(fuzz_schema(cases=10, seed=7))
        b = digests(fuzz_schema(cases=10, seed=7))
        assert a == b

    def test_different_seed_different_digests(self):
        a = digests(fuzz_schema(cases=20, seed=7))
        b = digests(fuzz_schema(cases=20, seed=8))
        assert a != b


class TestCleanRun:
    def test_real_codecs_fuzz_clean(self):
        report = fuzz_schema(cases=25)
        assert report["failures"] == []

    def test_every_handrolled_family_fuzzed(self):
        report = fuzz_schema(cases=2)
        families = set(report["families"])
        # One family per hand-rolled codec class...
        schema = load_repo_schema(repo_root())
        for name in codec_index(schema):
            assert name in families
        # ...plus the legacy goldens and the columnar frame.
        assert "columnar:ColumnarJobBlock" in families
        assert {f for f in families if f.startswith("legacy:")} >= {
            "legacy:Heartbeat",
            "legacy:DoneRequest",
            "legacy:RegisterWorkerRequest",
            "legacy:JobDescription",
            "legacy:RunJobRequest",
        }

    def test_unfuzzed_messages_are_protoc_owned(self):
        # Every schema message either has a hand-rolled codec (fuzzed),
        # is the columnar frame (its own family), or belongs to a
        # protoc-generated module (descriptor-checked instead) — no
        # message silently escapes all four gate layers.
        schema = load_repo_schema(repo_root())
        unfuzzed = {
            m.name for m in schema.messages
        } - set(codec_index(schema))
        assert unfuzzed == {
            "ColumnarJobBlock",
            "Empty",
            "InitJobRequest",
            "UpdateLeaseRequest",
            "UpdateLeaseResponse",
        }

    def test_protoc_mirror_covers_schema(self):
        pytest.importorskip("google.protobuf")
        schema = load_repo_schema(repo_root())
        mirror = build_protoc_mirror(schema)
        assert mirror is not None
        assert set(mirror) == {m.name for m in schema.messages}


class TestDetectsDrift:
    """The differential must FAIL when codec and schema disagree —
    otherwise the clean run above proves nothing."""

    def _mutated_explain_schema(self, old, new):
        root = repo_root()
        path = os.path.join(
            root, "shockwave_tpu", "runtime", "protobuf", "explain.proto"
        )
        with open(path, encoding="utf-8") as f:
            text = f.read()
        assert old in text
        return ProtoSchema.from_sources({"explain.proto": text.replace(old, new)})

    def test_renumbered_field_is_caught(self):
        pytest.importorskip("google.protobuf")
        schema = self._mutated_explain_schema(
            "string trace_context = 2;", "string trace_context = 3;"
        )
        report = fuzz_schema(
            schema, cases=20, messages=["ExplainJobRequest"]
        )
        assert any(
            "differ from protoc" in f for f in report["failures"]
        ), report["failures"]

    def test_retyped_field_is_caught(self):
        pytest.importorskip("google.protobuf")
        schema = self._mutated_explain_schema(
            "string narrative_json = 2;", "uint64 narrative_json = 2;"
        )
        report = fuzz_schema(
            schema, cases=20, messages=["ExplainJobResponse"]
        )
        assert report["failures"]


class TestDescriptorConformance:
    def test_protoc_and_legacy_descriptors_conform(self):
        pytest.importorskip("google.protobuf")
        assert descriptor_conformance_problems() == []

    def test_detects_descriptor_drift(self):
        pytest.importorskip("google.protobuf")
        # Remove UpdateLeaseResponse.extra_time, a field the generated
        # iterator_to_scheduler module carries: the conformance check
        # must demand regeneration.
        schema = load_repo_schema(repo_root())
        sources = {
            name: "".join(
                line
                for line in open(
                    os.path.join(
                        repo_root(),
                        "shockwave_tpu",
                        "runtime",
                        "protobuf",
                        name,
                    ),
                    encoding="utf-8",
                )
                if "extra_time" not in line
            )
            for name in list(schema.files)
        }
        mutated = ProtoSchema.from_sources(sources)
        problems = descriptor_conformance_problems(mutated)
        assert any("not in the live schema" in p for p in problems)


class TestModuleTables:
    def test_module_tables_match_disk(self):
        proto_dir = os.path.join(
            repo_root(), "shockwave_tpu", "runtime", "protobuf"
        )
        on_disk = {f for f in os.listdir(proto_dir) if f.endswith(".proto")}
        from shockwave_tpu.analysis.wirefuzz import PROTOC_MODULES

        assert set(HANDROLLED_MODULES) | set(PROTOC_MODULES) == on_disk
        assert set(LEGACY_MODULES) <= set(HANDROLLED_MODULES)


class TestWireSmokeGate:
    def test_gate_passes_on_the_repo(self):
        root = repo_root()
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(root, "scripts", "ci", "wire_smoke.py"),
                "--cases",
                "5",
            ],
            cwd=root,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "wire smoke gate PASS" in proc.stdout

    def test_cli_fuzzer_entrypoint(self):
        root = repo_root()
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "shockwave_tpu.analysis.wirefuzz",
                "--cases",
                "3",
                "--json",
            ],
            cwd=root,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert '"failures": []' in proc.stdout
