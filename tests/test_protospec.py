"""protospec: the hand-rolled .proto parser the wire-contract analyzer
(wirecheck rules, wire registry, wirefuzz) is built on. Fixture-text
units plus assertions over the real repo schema, so a parser regression
cannot silently blind the whole analysis layer.
"""

import pytest

from shockwave_tpu.analysis import parse_proto_text, repo_root
from shockwave_tpu.analysis.protospec import (
    IMPLEMENTATION_RESERVED,
    WIRE_FIXED64,
    WIRE_LEN,
    WIRE_VARINT,
    ProtoSchema,
    load_repo_schema,
)

FIXTURE = """
// A comment with message Decoy { uint64 nope = 9; } inside.
syntax = "proto3";

package fixture;

/* block comment
   string also_decoy = 3; */

enum Color {
  COLOR_UNSPECIFIED = 0;
  RED = 1;
  BLUE = 2;
}

message Inner {
  string label = 1;  // trailing comment
}

message Outer {
  reserved 5, 10 to 12;
  reserved "old_name";
  uint64 id = 1;
  string name = 2;
  repeated uint64 steps = 3;
  repeated double weights = 4;
  repeated string tags = 6;
  Inner inner = 7;
  repeated Inner inners = 8;
  bool flag = 9;
  Color color = 13;
  bytes payload = 14;
  double score = 15;
}

service FixtureService {
  rpc GetOuter (Inner) returns (Outer);
}
"""


@pytest.fixture(scope="module")
def schema():
    return ProtoSchema({"fixture.proto": parse_proto_text(FIXTURE, "fixture.proto")})


class TestParser:
    def test_messages_enums_services(self, schema):
        assert {m.name for m in schema.messages} == {"Inner", "Outer"}
        assert [e.name for e in schema.enums] == ["Color"]
        (svc,) = schema.services
        assert svc.name == "FixtureService"
        (method,) = svc.methods
        assert (method.name, method.request, method.response) == (
            "GetOuter",
            "Inner",
            "Outer",
        )

    def test_comments_do_not_declare_fields(self, schema):
        assert schema.message("Decoy") is None
        outer = schema.message("Outer")
        assert "also_decoy" not in outer.by_name

    def test_field_numbers_types_and_labels(self, schema):
        outer = schema.message("Outer")
        assert sorted(outer.by_number) == [1, 2, 3, 4, 6, 7, 8, 9, 13, 14, 15]
        assert outer.by_name["id"].type == "uint64"
        assert not outer.by_name["id"].repeated
        assert outer.by_name["steps"].repeated
        assert outer.by_name["inner"].type == "Inner"

    def test_wire_kind_resolution(self, schema):
        outer = schema.message("Outer")
        by = outer.by_name
        assert by["id"].kind == "varint"
        assert by["id"].wire_type == WIRE_VARINT
        assert by["name"].kind == "string"
        assert by["name"].wire_type == WIRE_LEN
        assert by["score"].kind == "fixed64"
        assert by["score"].wire_type == WIRE_FIXED64
        assert by["flag"].kind == "varint"
        assert by["payload"].kind == "bytes"
        assert by["inner"].kind == "message"
        assert by["color"].kind == "enum"
        assert by["color"].wire_type == WIRE_VARINT

    def test_repeated_numeric_scalars_are_packed(self, schema):
        outer = schema.message("Outer")
        steps = outer.by_name["steps"]
        assert steps.packed
        assert steps.wire_type == WIRE_LEN
        assert steps.element_wire_type == WIRE_VARINT
        weights = outer.by_name["weights"]
        assert weights.packed
        assert weights.element_wire_type == WIRE_FIXED64
        # Repeated strings/messages are NOT packed: one LEN field each.
        assert not outer.by_name["tags"].packed
        assert not outer.by_name["inners"].packed

    def test_reserved(self, schema):
        outer = schema.message("Outer")
        assert outer.reserved_hit(5)
        assert outer.reserved_hit(11)
        assert not outer.reserved_hit(4)
        assert "old_name" in outer.reserved_names
        lo, hi = IMPLEMENTATION_RESERVED
        assert outer.reserved_hit(lo) and outer.reserved_hit(hi)

    def test_cross_file_enum_resolution(self):
        a = parse_proto_text(
            'syntax = "proto3"; package p;\n'
            "enum Mood { OK = 0; BAD = 1; }",
            "a.proto",
        )
        b = parse_proto_text(
            'syntax = "proto3"; package p;\n'
            "message M { Mood mood = 1; }",
            "b.proto",
        )
        schema = ProtoSchema({"a.proto": a, "b.proto": b})
        assert schema.message("M").by_name["mood"].kind == "enum"

    def test_from_sources(self):
        schema = ProtoSchema.from_sources(
            {"x.proto": 'syntax = "proto3"; message X { uint32 n = 1; }'}
        )
        assert schema.message("X").by_name["n"].wire_type == WIRE_VARINT


class TestRepoSchema:
    """The real schema: the analyzer's view of the actual wire contract."""

    def test_all_proto_files_parse(self):
        schema = load_repo_schema(repo_root())
        assert len(schema.files) == 8
        names = set(schema.files)
        assert "explain.proto" in names  # authored this PR
        assert "common.proto" in names

    def test_known_shapes(self):
        schema = load_repo_schema(repo_root())
        jobspec = schema.message("JobSpec")
        assert len(jobspec.fields) == 13
        assert jobspec.by_name["needs_data_dir"].type == "bool"
        heartbeat = schema.message("Heartbeat")
        assert heartbeat.by_name["job_state"].kind == "message"
        assert heartbeat.by_name["job_state"].repeated
        done = schema.message("DoneRequest")
        assert done.by_name["num_steps"].packed
        assert done.by_name["execution_time"].packed
        # Cross-file: JobState.status is an enum declared in enums.proto.
        assert schema.message("JobState").by_name["status"].kind == "enum"

    def test_services_present(self):
        schema = load_repo_schema(repo_root())
        assert {s.name for s in schema.services} >= {
            "SchedulerToWorker",
            "WorkerToScheduler",
            "SchedulerExplain",
        }

    def test_schema_cache_returns_same_object(self):
        root = repo_root()
        assert load_repo_schema(root) is load_repo_schema(root)
