"""Ring attention must match dense causal attention exactly (up to float
tolerance) on a sequence-sharded mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shockwave_tpu.parallel.mesh import make_mesh
from shockwave_tpu.parallel.ring_attention import (
    dense_causal_attention,
    ring_attention,
)


@pytest.mark.parametrize("seq_shards", [2, 4])
def test_matches_dense_attention(seq_shards):
    mesh = make_mesh((1, 1, seq_shards), devices=jax.devices()[:seq_shards])
    rng = np.random.default_rng(0)
    B, S, H, D = 2, 8 * seq_shards, 2, 4
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    out_ring = ring_attention(q, k, v, mesh)
    out_dense = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), rtol=2e-4, atol=2e-5
    )


def test_combined_data_model_seq_mesh():
    mesh = make_mesh((2, 2, 2))
    rng = np.random.default_rng(1)
    B, S, H, D = 4, 16, 4, 8
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    out_ring = ring_attention(q, k, v, mesh)
    out_dense = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), rtol=2e-4, atol=2e-5
    )


@pytest.mark.slow
def test_grad_flows_through_ring():
    mesh = make_mesh((1, 1, 2), devices=jax.devices()[:2])
    rng = np.random.default_rng(2)
    B, S, H, D = 1, 8, 1, 4
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)

    def loss_ring(q):
        return jnp.sum(ring_attention(q, k, v, mesh) ** 2)

    def loss_dense(q):
        return jnp.sum(dense_causal_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring)(q)
    g_dense = jax.grad(loss_dense)(q)
    np.testing.assert_allclose(
        np.asarray(g_ring), np.asarray(g_dense), rtol=1e-3, atol=1e-4
    )


@pytest.mark.parametrize("seq_shards", [2, 4])
def test_flash_inner_matches_dense(seq_shards):
    """The Pallas-flash hop body (lane-aligned local blocks) must agree
    with dense causal attention, like the einsum body does."""
    mesh = make_mesh((1, 1, seq_shards), devices=jax.devices()[:seq_shards])
    rng = np.random.default_rng(5)
    B, S, H, D = 1, 128 * seq_shards, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    # At S_local = 128 "auto" must pick the flash body on its own.
    out_ring = ring_attention(q, k, v, mesh)
    out_flash = ring_attention(q, k, v, mesh, inner="flash")
    out_dense = dense_causal_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_flash), rtol=0, atol=0
    )
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), rtol=2e-4, atol=2e-5
    )


@pytest.mark.slow
def test_flash_inner_grad_matches_dense():
    """Gradients through the flash hop body (incl. the lse cotangent of
    the hop merge) must match the dense reference."""
    mesh = make_mesh((1, 1, 2), devices=jax.devices()[:2])
    rng = np.random.default_rng(6)
    B, S, H, D = 1, 256, 1, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, inner="flash") ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal_attention(q, k, v) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=1e-3, atol=1e-4
        )


@pytest.mark.parametrize("inner", ["flash", "dense"])
def test_gqa_ring_matches_dense(inner):
    """Ring attention with grouped-query KV: the flash body reads the
    shared heads through the kernel index maps (and ppermutes the
    small tensors); the dense body repeats up front. Both must match
    single-device dense attention on the repeated KV."""
    mesh = make_mesh((1, 1, 2), devices=jax.devices()[:2])
    rng = np.random.default_rng(7)
    B, S, H, Hkv, D = 1, 256, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    out_ring = ring_attention(q, k, v, mesh, inner=inner)
    rep = lambda x: jnp.repeat(x, H // Hkv, axis=2)  # noqa: E731
    out_dense = dense_causal_attention(q, rep(k), rep(v))
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), rtol=2e-4,
        atol=2e-5,
    )


@pytest.mark.slow
def test_gqa_ring_grad_matches_dense():
    mesh = make_mesh((1, 1, 2), devices=jax.devices()[:2])
    rng = np.random.default_rng(8)
    B, S, H, Hkv, D = 1, 256, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    rep = lambda x: jnp.repeat(x, H // Hkv, axis=2)  # noqa: E731

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh, inner="flash") ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal_attention(q, rep(k), rep(v)) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        assert gr.shape == gd.shape
        np.testing.assert_allclose(
            np.asarray(gr), np.asarray(gd), rtol=1e-3, atol=1e-4
        )
