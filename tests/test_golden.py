"""Golden end-to-end metrics on the committed standalone trace.

The reference's integration test diffs a full simulator log against a
golden file (reference: scheduler/tests/scheduler_tests.py:10-27, whose
fixtures are missing from its snapshot); here the pinned contract is the
headline metrics of deterministic runs on the committed 12-job trace.
If an intentional behavior change moves these, update the constants in
the same commit and say why.
"""

import os

import pytest

from shockwave_tpu.core.scheduler import Scheduler
from shockwave_tpu.data import load_or_synthesize_profiles, parse_trace
from shockwave_tpu.data.default_oracle import generate_oracle
from shockwave_tpu.policies import get_policy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACE = os.path.join(REPO, "traces", "small_12_dynamic.trace")

GOLDEN = {
    "fifo": dict(makespan=12376.656, avg_jct=5691.573, worst_ftf=3.416),
    "max_min_fairness": dict(
        makespan=12976.601, avg_jct=5178.854, worst_ftf=2.116
    ),
    # Planner backends (deterministic: C++ greedy / jitted level-set
    # solve; pinning them guards the whole plan->round pipeline, not
    # just the solver objective). Re-pinned for the Dirichlet
    # change-point reweight (JobMetadata._regime_posterior): this
    # trace's gns/accordion jobs switch batch size at measured epochs
    # the profile pattern mis-places, and anchoring the posterior on
    # the observed regime improved both backends' makespans
    # (native 13336.436 -> 12976.464, level 13696.373 -> 13456.422).
    "shockwave_native": dict(
        makespan=12976.464, avg_jct=5745.960, worst_ftf=2.029
    ),
    "shockwave_tpu_level": dict(
        makespan=13456.422, avg_jct=5658.689, worst_ftf=2.029
    ),
}

SHOCKWAVE_CONFIG = {
    "num_gpus": 8,
    "time_per_iteration": 120,
    "future_rounds": 20,
    "lambda": 5.0,
    "k": 10.0,
}


@pytest.mark.parametrize("policy_name", sorted(GOLDEN))
def test_golden_metrics_on_committed_trace(policy_name):
    if policy_name == "shockwave_native":
        from shockwave_tpu import native

        if not native.available():
            pytest.skip("no C++ compiler")
    jobs, arrivals = parse_trace(TRACE)
    oracle = generate_oracle()
    profiles = load_or_synthesize_profiles(TRACE, jobs, oracle, cache=False)
    for i, job in enumerate(jobs):
        job.duration = sum(profiles[i]["duration_every_epoch"])
    sched = Scheduler(
        get_policy(policy_name, seed=0),
        throughputs=oracle,
        seed=0,
        time_per_iteration=120,
        profiles=profiles,
        shockwave_config=(
            dict(SHOCKWAVE_CONFIG)
            if policy_name.startswith("shockwave")
            else None
        ),
    )
    makespan = sched.simulate({"v100": 8}, arrivals, jobs)
    ftf_list, _ = sched.get_finish_time_fairness()
    expected = GOLDEN[policy_name]
    assert makespan == pytest.approx(expected["makespan"], abs=1e-3)
    assert sched.get_average_jct() == pytest.approx(
        expected["avg_jct"], abs=1e-3
    )
    assert max(ftf_list) == pytest.approx(expected["worst_ftf"], abs=1e-3)
