"""shockwave-lint: fixture corpus per rule (positive / negative /
suppressed), baseline-ratchet semantics, CLI contract, and the tier-1
repo-wide gate asserting zero findings beyond the committed baseline.
"""

import json
import subprocess
import sys

import pytest

from shockwave_tpu.analysis import (
    active,
    check_source,
    default_rules,
    diff_against_baseline,
    load_baseline,
    make_baseline,
    repo_root,
    rule_by_name,
    run_paths,
    save_baseline,
)


def findings_for(source, relpath, rule_name):
    """Active (non-suppressed) findings of one rule over a snippet."""
    return [
        f
        for f in check_source(source, relpath, [rule_by_name(rule_name)])
        if not f.suppressed
    ]


# -- rule 1: donation-after-use ----------------------------------------

DONATION_POSITIVE = """
import jax

def train(variables, opt_state, batches):
    jit_step = jax.jit(step, donate_argnums=(0, 1))
    new_v, new_o, loss = jit_step(variables, opt_state, batches[0])
    print(variables["params"])  # read of the donated buffer
"""

DONATION_NEGATIVE = """
import jax

def train(variables, opt_state, loader):
    jit_step = jax.jit(step, donate_argnums=(0, 1))
    for batch in loader:
        variables, opt_state, loss = jit_step(variables, opt_state, batch)
    return variables, opt_state, loss
"""

DONATION_DECORATOR_POSITIVE = """
import functools
import jax

def bench(state, batch):
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, batch):
        return state

    out = step(state, batch)
    return state  # donated 'state' read after the call
"""

DONATION_SUPPRESSED = """
import jax

def train(variables, opt_state, batch):
    jit_step = jax.jit(step, donate_argnums=(0, 1))
    new_v, new_o, loss = jit_step(variables, opt_state, batch)
    # shockwave-lint: disable=donation-after-use
    print(variables["params"])
"""


class TestDonationAfterUse:
    def test_positive(self):
        hits = findings_for(DONATION_POSITIVE, "shockwave_tpu/models/x.py",
                            "donation-after-use")
        assert len(hits) == 1
        assert "'variables'" in hits[0].message
        assert hits[0].line == 7

    def test_negative_rebinding_idiom(self):
        assert not findings_for(DONATION_NEGATIVE,
                                "shockwave_tpu/models/x.py",
                                "donation-after-use")

    def test_decorator_form(self):
        hits = findings_for(DONATION_DECORATOR_POSITIVE,
                            "scripts/bench_x.py", "donation-after-use")
        assert len(hits) == 1
        assert "'state'" in hits[0].message

    def test_suppressed(self):
        assert not findings_for(DONATION_SUPPRESSED,
                                "shockwave_tpu/models/x.py",
                                "donation-after-use")
        suppressed = [
            f
            for f in check_source(
                DONATION_SUPPRESSED, "shockwave_tpu/models/x.py",
                [rule_by_name("donation-after-use")],
            )
            if f.suppressed
        ]
        assert len(suppressed) == 1


# -- rule 2: host-sync-in-hot-loop -------------------------------------

HOTLOOP_POSITIVE_TRAIN = """
import jax
import numpy as np

def train(loader, state):
    jit_step = jax.jit(step)
    for batch in loader:
        state, loss = jit_step(state, batch)
        print(float(loss))  # host sync every iteration
"""

HOTLOOP_POSITIVE_SCAN = """
import jax
import numpy as np

def solve(xs):
    def body(carry, x):
        host = np.asarray(x)  # tracer leak / forced sync
        return carry, host

    return jax.lax.scan(body, 0, xs)
"""

HOTLOOP_NEGATIVE = """
import jax
import jax.numpy as jnp

def train(loader, state):
    jit_step = jax.jit(step)
    for batch in loader:
        state, loss = jit_step(state, batch)
    return float(loss)  # after the loop: fine
"""

HOTLOOP_OUT_OF_SCOPE = """
def run(loader, state):
    import jax
    jit_step = jax.jit(step)
    for batch in loader:
        state, loss = jit_step(state, batch)
        print(float(loss))
"""

HOTLOOP_SUPPRESSED = """
import jax

def train(loader, state):
    jit_step = jax.jit(step)
    for batch in loader:
        state, loss = jit_step(state, batch)
        # shockwave-lint: disable=host-sync-in-hot-loop
        loss.block_until_ready()
"""


class TestHostSyncInHotLoop:
    def test_train_loop_positive(self):
        hits = findings_for(HOTLOOP_POSITIVE_TRAIN,
                            "shockwave_tpu/models/x.py",
                            "host-sync-in-hot-loop")
        assert len(hits) == 1
        assert "float()" in hits[0].message

    def test_scan_body_positive(self):
        hits = findings_for(HOTLOOP_POSITIVE_SCAN,
                            "shockwave_tpu/solver/eg_jax.py",
                            "host-sync-in-hot-loop")
        assert len(hits) == 1
        assert "np.asarray" in hits[0].message

    def test_negative_after_loop(self):
        assert not findings_for(HOTLOOP_NEGATIVE,
                                "shockwave_tpu/models/x.py",
                                "host-sync-in-hot-loop")

    def test_scoped_to_hot_packages(self):
        # Identical code outside models//parallel//eg_jax.py: no finding.
        assert not findings_for(HOTLOOP_OUT_OF_SCOPE,
                                "shockwave_tpu/core/x.py",
                                "host-sync-in-hot-loop")

    def test_suppressed(self):
        assert not findings_for(HOTLOOP_SUPPRESSED,
                                "shockwave_tpu/models/x.py",
                                "host-sync-in-hot-loop")


# -- rule 3: rng-key-reuse ---------------------------------------------

RNG_POSITIVE = """
import jax

def init(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (4,))
    b = jax.random.normal(key, (4,))  # identical samples
    return a, b
"""

RNG_NEGATIVE_SPLIT = """
import jax

def init(seed):
    key = jax.random.PRNGKey(seed)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (4,))
    b = jax.random.normal(k2, (4,))
    return a, b
"""

RNG_NEGATIVE_STRING_SPLIT = """
def parse(line):
    a, b = line.split("\\t")
    c = int(a)
    d = int(a)
    return c, d, b
"""

RNG_NEGATIVE_BRANCHES = """
import jax

def init(seed, kind):
    key = jax.random.PRNGKey(seed)
    if kind == "normal":
        out = jax.random.normal(key, (4,))
        return out
    out = jax.random.uniform(key, (4,))
    return out
"""

RNG_SUPPRESSED = """
import jax

def init(seed):
    key = jax.random.PRNGKey(seed)
    a = jax.random.normal(key, (4,))
    # shockwave-lint: disable=rng-key-reuse
    b = jax.random.normal(key, (4,))
    return a, b
"""


class TestRngKeyReuse:
    def test_positive(self):
        hits = findings_for(RNG_POSITIVE, "shockwave_tpu/models/x.py",
                            "rng-key-reuse")
        assert len(hits) == 1
        assert "'key'" in hits[0].message

    def test_negative_split(self):
        assert not findings_for(RNG_NEGATIVE_SPLIT,
                                "shockwave_tpu/models/x.py",
                                "rng-key-reuse")

    def test_string_split_not_a_key(self):
        assert not findings_for(RNG_NEGATIVE_STRING_SPLIT,
                                "shockwave_tpu/data/x.py",
                                "rng-key-reuse")

    def test_terminating_branches_are_exclusive(self):
        assert not findings_for(RNG_NEGATIVE_BRANCHES,
                                "shockwave_tpu/models/x.py",
                                "rng-key-reuse")

    def test_suppressed(self):
        assert not findings_for(RNG_SUPPRESSED,
                                "shockwave_tpu/models/x.py",
                                "rng-key-reuse")


# -- rule 4: lock-discipline -------------------------------------------

LOCK_POSITIVE = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._series = {}
        self.enabled = False

    def set_enabled(self, value):
        self.enabled = value  # unguarded write

    def record(self, name, value):
        with self._lock:
            self._series[name] = value
"""

LOCK_NEGATIVE = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._series = {}

    def record(self, name, value):
        with self._lock:
            self._series[name] = value
"""

LOCK_CALLER_HOLDS = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._series = {}

    def record(self, name, value):
        with self._lock:
            self._store(name, value)

    def _store(self, name, value):
        \"\"\"Caller holds the lock.\"\"\"
        self._series[name] = value
"""

LOCK_SUPPRESSED = """
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = False

    def set_enabled(self, value):
        # shockwave-lint: disable=lock-discipline
        self.enabled = value
"""


class TestLockDiscipline:
    def test_positive(self):
        hits = findings_for(LOCK_POSITIVE, "shockwave_tpu/obs/x.py",
                            "lock-discipline")
        assert len(hits) == 1
        assert "set_enabled" in hits[0].message

    def test_negative(self):
        assert not findings_for(LOCK_NEGATIVE, "shockwave_tpu/obs/x.py",
                                "lock-discipline")

    def test_caller_holds_lock_contract(self):
        assert not findings_for(LOCK_CALLER_HOLDS,
                                "shockwave_tpu/obs/x.py",
                                "lock-discipline")

    def test_scoped_to_threaded_packages(self):
        assert not findings_for(LOCK_POSITIVE,
                                "shockwave_tpu/solver/x.py",
                                "lock-discipline")

    def test_suppressed(self):
        assert not findings_for(LOCK_SUPPRESSED, "shockwave_tpu/obs/x.py",
                                "lock-discipline")


# -- rule 5: non-atomic-artifact-write ---------------------------------

WRITE_POSITIVE = """
import json

def save(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)
"""

WRITE_NEGATIVE = """
import json
from shockwave_tpu.utils.fileio import atomic_write_json

def save(path, obj):
    atomic_write_json(path, obj)

def load(path):
    with open(path) as f:
        return json.load(f)
"""

WRITE_BINARY_NEGATIVE = """
def save(path, blob):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
"""

WRITE_SUPPRESSED = """
def open_sink(path):
    # live stream for a subprocess, not an artifact
    # shockwave-lint: disable=non-atomic-artifact-write
    return open(path, "w")
"""


class TestNonAtomicArtifactWrite:
    def test_positive(self):
        hits = findings_for(WRITE_POSITIVE, "scripts/analysis/x.py",
                            "non-atomic-artifact-write")
        assert len(hits) == 1

    def test_negative(self):
        assert not findings_for(WRITE_NEGATIVE, "scripts/analysis/x.py",
                                "non-atomic-artifact-write")

    def test_binary_checkpoint_path_not_flagged(self):
        assert not findings_for(WRITE_BINARY_NEGATIVE,
                                "shockwave_tpu/models/x.py",
                                "non-atomic-artifact-write")

    def test_tests_exempt(self):
        assert not findings_for(WRITE_POSITIVE, "tests/test_x.py",
                                "non-atomic-artifact-write")

    def test_suppressed(self):
        assert not findings_for(WRITE_SUPPRESSED, "scripts/x.py",
                                "non-atomic-artifact-write")


# -- rule 6: solver-backend-conformance --------------------------------

BACKEND_POSITIVE = """
import numpy as np

def solve_eg_newbackend(problem):
    # optimizes welfare + makespan but silently drops the
    # switching-cost term
    return np.zeros((problem.num_jobs, problem.future_rounds))
"""

BACKEND_NEGATIVE = """
import numpy as np

def solve_eg_newbackend(problem):
    bonus = problem.switch_bonus()
    return np.zeros((problem.num_jobs, problem.future_rounds))
"""

BACKEND_BAD_SIGNATURE = """
def solve_eg_newbackend(costs, switch_bonus, incumbent):
    return costs
"""


class TestSolverBackendConformance:
    def test_missing_switch_term(self):
        hits = findings_for(BACKEND_POSITIVE,
                            "shockwave_tpu/solver/eg_newbackend.py",
                            "solver-backend-conformance")
        assert len(hits) == 1
        assert "switch" in hits[0].message

    def test_conformant_backend(self):
        assert not findings_for(BACKEND_NEGATIVE,
                                "shockwave_tpu/solver/eg_newbackend.py",
                                "solver-backend-conformance")

    def test_entry_signature(self):
        hits = findings_for(BACKEND_BAD_SIGNATURE,
                            "shockwave_tpu/solver/eg_newbackend.py",
                            "solver-backend-conformance")
        assert any("first parameter" in f.message for f in hits)

    def test_scoped_to_solver_modules(self):
        assert not findings_for(BACKEND_POSITIVE,
                                "shockwave_tpu/core/x.py",
                                "solver-backend-conformance")

    def test_real_backends_and_planner_conform(self):
        # The live solver stack must stay clean under this rule.
        findings = run_paths(
            ["shockwave_tpu/solver", "shockwave_tpu/policies",
             "shockwave_tpu/native"],
            rules=[rule_by_name("solver-backend-conformance")],
        )
        assert not active(findings), [f.render() for f in findings]


# Planner-facade fixtures: dispatch-table + degradation-ladder
# registration (the backend count ratchet: 7 registered branches
# including the cell-decomposed "cells" dispatch, and the pdhg rung
# between primary and relaxed).

_PLANNER_DISPATCH = """
        if backend == "reference":
            return 1
        if backend == "native":
            return 1
        if backend == "level":
            return 1
        if backend == "sharded":
            return 1
        if backend == "relaxed":
            return 1
        if backend == "pdhg":
            return 1
        if backend == "cells":
            return 1
        return 0
"""

_PLANNER_TEMPLATE = """
from shockwave_tpu.solver.eg_problem import EGProblem


class Planner:
    def _build_problem(self, arrays):
        return EGProblem(
            priorities=arrays.p,
            switch_cost=arrays.sc,
            incumbent=arrays.inc,
        )

    def _ladder_rungs(self):
        rungs = [self.backend]
        for fallback in ({ladder}):
            if fallback not in rungs:
                rungs.append(fallback)
        return rungs

    def _solve_backend(self, backend, problem):
{dispatch}
"""

PLANNER_CONFORMANT = _PLANNER_TEMPLATE.format(
    ladder='"pdhg", "relaxed", "native"', dispatch=_PLANNER_DISPATCH
)
PLANNER_NO_PDHG_DISPATCH = _PLANNER_TEMPLATE.format(
    ladder='"pdhg", "relaxed", "native"',
    dispatch=_PLANNER_DISPATCH.replace(
        '        if backend == "pdhg":\n            return 1\n', ""
    ),
)
PLANNER_NO_PDHG_RUNG = _PLANNER_TEMPLATE.format(
    ladder='"relaxed", "native"', dispatch=_PLANNER_DISPATCH
)
PLANNER_NO_LADDER = PLANNER_CONFORMANT.replace("_ladder_rungs", "_rungs")

_PLANNER_PATH = "shockwave_tpu/policies/shockwave.py"


class TestPlannerLadderConformance:
    def test_conformant_planner_is_clean(self):
        assert not findings_for(PLANNER_CONFORMANT, _PLANNER_PATH,
                                "solver-backend-conformance")

    def test_missing_pdhg_dispatch_branch(self):
        hits = findings_for(PLANNER_NO_PDHG_DISPATCH, _PLANNER_PATH,
                            "solver-backend-conformance")
        assert len(hits) == 1
        assert "'pdhg'" in hits[0].message
        assert "dispatch" in hits[0].message

    def test_missing_pdhg_ladder_rung(self):
        hits = findings_for(PLANNER_NO_PDHG_RUNG, _PLANNER_PATH,
                            "solver-backend-conformance")
        assert len(hits) == 1
        assert "ladder" in hits[0].message
        assert "'pdhg'" in hits[0].message

    def test_missing_ladder_function(self):
        hits = findings_for(PLANNER_NO_LADDER, _PLANNER_PATH,
                            "solver-backend-conformance")
        assert any("_ladder_rungs" in f.message for f in hits)

    def test_scoped_to_planner_file(self):
        assert not findings_for(PLANNER_NO_PDHG_RUNG,
                                "shockwave_tpu/policies/other.py",
                                "solver-backend-conformance")


# -- framework: suppressions, parse errors ------------------------------

def test_suppression_line_above_and_trailing():
    above = """
x = 1
# shockwave-lint: disable=non-atomic-artifact-write
f = open("out.json", "w")
"""
    trailing = """
f = open("out.json", "w")  # shockwave-lint: disable=non-atomic-artifact-write
"""
    for src in (above, trailing):
        assert not findings_for(src, "scripts/x.py",
                                "non-atomic-artifact-write")


def test_suppression_is_rule_specific():
    src = """
# shockwave-lint: disable=rng-key-reuse
f = open("out.json", "w")
"""
    assert len(findings_for(src, "scripts/x.py",
                            "non-atomic-artifact-write")) == 1


def test_parse_error_is_a_finding_not_a_crash():
    findings = check_source("def broken(:\n", "scripts/x.py",
                            default_rules())
    assert len(findings) == 1
    assert findings[0].rule == "parse-error"


# -- baseline ratchet ---------------------------------------------------

def test_baseline_roundtrip_and_ratchet(tmp_path):
    src_v1 = WRITE_POSITIVE
    findings = findings_for(src_v1, "scripts/x.py",
                            "non-atomic-artifact-write")
    bl = make_baseline(findings)
    path = tmp_path / "baseline.json"
    save_baseline(str(path), bl)
    loaded = load_baseline(str(path))
    assert len(loaded["entries"]) == 1

    # Unchanged code: no new findings, nothing stale.
    new, stale = diff_against_baseline(findings, loaded)
    assert not new and not stale

    # Line shift (edit above the finding): fingerprint still matches.
    shifted = "import os\n" + src_v1
    shifted_findings = findings_for(shifted, "scripts/x.py",
                                    "non-atomic-artifact-write")
    new, stale = diff_against_baseline(shifted_findings, loaded)
    assert not new and not stale

    # A second, distinct violation: NEW (occurrence index differs).
    two = src_v1 + '\n\ndef save2(path, obj):\n    with open(path, "w") as f:\n        pass\n'
    two_findings = findings_for(two, "scripts/x.py",
                                "non-atomic-artifact-write")
    new, stale = diff_against_baseline(two_findings, loaded)
    assert len(new) == 1 and not stale

    # Violation fixed: the baseline entry goes stale (ratchet trips).
    new, stale = diff_against_baseline([], loaded)
    assert not new and len(stale) == 1


def test_empty_baseline_means_any_finding_is_new():
    findings = findings_for(WRITE_POSITIVE, "scripts/x.py",
                            "non-atomic-artifact-write")
    new, stale = diff_against_baseline(findings, {"entries": []})
    assert len(new) == 1 and not stale


# -- tier-1 repo-wide gate ---------------------------------------------

def test_repo_is_clean_against_baseline():
    """The committed tree must carry zero findings beyond the committed
    baseline, and the baseline must carry zero stale entries — the same
    ratchet scripts/ci/lint.py enforces, here so tier-1 enforces it."""
    findings = active(run_paths())
    baseline = load_baseline(
        str(__import__("pathlib").Path(repo_root()) / "lint_baseline.json")
    )
    new, stale = diff_against_baseline(findings, baseline)
    assert not new, "new lint findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert not stale, f"stale baseline entries (run --write-baseline): {stale}"


def test_every_rule_has_a_docstringed_catalog_entry():
    from shockwave_tpu.analysis.rules import RULE_CLASSES

    assert len(RULE_CLASSES) >= 6
    for cls in RULE_CLASSES:
        assert cls.name and cls.description and cls.rationale


# -- CLI ----------------------------------------------------------------

def test_cli_json_and_exit_codes(tmp_path):
    from shockwave_tpu.analysis.cli import main

    bad = tmp_path / "shockwave_tpu"
    bad.mkdir()
    victim = bad / "bad_script.py"
    victim.write_text(WRITE_POSITIVE)
    baseline = tmp_path / "bl.json"

    # New finding against an empty baseline -> exit 1.
    rc = main([str(victim), "--baseline", str(baseline)])
    assert rc == 1

    # Accept it, then the same run is clean -> exit 0.
    rc = main([str(victim), "--baseline", str(baseline),
               "--write-baseline"])
    assert rc == 0
    rc = main([str(victim), "--baseline", str(baseline)])
    assert rc == 0

    # Fix the violation; the ledger is now stale -> exit 2.
    victim.write_text("x = 1\n")
    rc = main([str(victim), "--baseline", str(baseline)])
    assert rc == 2


def test_cli_subprocess_smoke():
    out = subprocess.run(
        [sys.executable, "-m", "shockwave_tpu.analysis", "--list-rules"],
        capture_output=True, text=True, cwd=repo_root(),
    )
    assert out.returncode == 0
    for name in ("donation-after-use", "host-sync-in-hot-loop",
                 "rng-key-reuse", "lock-discipline",
                 "non-atomic-artifact-write",
                 "solver-backend-conformance"):
        assert name in out.stdout


def test_cli_json_shape():
    out = subprocess.run(
        [sys.executable, "-m", "shockwave_tpu.analysis", "--json"],
        capture_output=True, text=True, cwd=repo_root(),
    )
    payload = json.loads(out.stdout)
    for key in ("total_findings", "suppressed", "new_findings",
                "stale_baseline_entries", "findings"):
        assert key in payload
