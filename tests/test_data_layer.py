"""Data-layer tests: trace I/O, throughput oracles, batch-size schedules,
profile synthesis. Mirrors the reference's fast deterministic test style
(reference: scheduler/tests/policies_tests.py uses tiny hand-built inputs)."""

import glob
import math
import os

import pytest

from shockwave_tpu.core.ids import JobId
from shockwave_tpu.core.job import Job
from shockwave_tpu.data import bs_patterns, parse_trace, write_trace
from shockwave_tpu.data.default_oracle import generate_oracle
from shockwave_tpu.data.profiles import synthesize_profiles
from shockwave_tpu.data.throughputs import read_throughputs, stringify_throughputs
from shockwave_tpu.data.workload_info import num_epochs, steps_per_epoch

REFERENCE_TRACES = sorted(
    glob.glob("/root/reference/scheduler/traces/shockwave/*.trace")
)


def test_job_id_ordering_and_overlap():
    a, b = JobId(1), JobId(2)
    pair = JobId(2, 1)
    assert pair.is_pair and pair.as_tuple() == (1, 2)
    assert a < b < JobId(2, 3)
    assert a.overlaps_with(pair) and b.overlaps_with(pair)
    assert not JobId(3).overlaps_with(pair)
    assert sorted([pair, b, a]) == [a, b, pair]
    assert JobId(5) == 5
    with pytest.raises(ValueError):
        pair.overlaps_with(a)


def test_job_batch_size_update():
    job = Job(
        job_type="LM (batch size 10)",
        command="python3 main.py --cuda --data %s/wikitext2 --batch_size 10",
    )
    assert job.model == "LM" and job.batch_size == 10
    job.update_batch_size(20)
    assert job.batch_size == 20
    assert job.command.endswith("--batch_size 20")
    # Translation commands carry a trailing flag after the batch size.
    tj = Job(
        job_type="Transformer (batch size 64)",
        command=(
            "python3 train.py -data %s/translation/multi30k.atok.low.pt"
            " -batch_size 64 -proj_share_weight"
        ),
    )
    tj.update_batch_size(128)
    assert "-batch_size 128 -proj_share_weight" in tj.command
    assert tj.job_type == "Transformer (batch size 128)"


@pytest.mark.skipif(not REFERENCE_TRACES, reason="reference traces unavailable")
def test_parse_reference_traces():
    for trace in REFERENCE_TRACES:
        jobs, arrivals = parse_trace(trace)
        assert len(jobs) == len(arrivals) > 0
        assert arrivals == sorted(arrivals)
        for job in jobs:
            assert job.scale_factor >= 1
            assert job.mode in ("static", "accordion", "gns")
            assert job.batch_size > 0 and job.model


def test_trace_roundtrip(tmp_path):
    jobs = [
        Job(
            job_type="ResNet-18 (batch size 32)",
            command="python3 main.py --data_dir=%s/cifar10 --batch_size 32",
            working_directory="image_classification/cifar10",
            num_steps_arg="--num_steps",
            total_steps=5000,
            duration=1234.0,
            scale_factor=2,
            mode="accordion",
        )
    ]
    path = str(tmp_path / "t.trace")
    write_trace(path, jobs, [17.0])
    jobs2, arrivals2 = parse_trace(path)
    assert arrivals2 == [17.0]
    assert jobs2[0].job_type == jobs[0].job_type
    assert jobs2[0].total_steps == 5000
    assert jobs2[0].scale_factor == 2
    assert jobs2[0].mode == "accordion"


def test_throughputs_roundtrip(tmp_path):
    import json

    oracle = generate_oracle()
    path = str(tmp_path / "oracle.json")
    with open(path, "w") as f:
        json.dump(stringify_throughputs(oracle), f)
    parsed = read_throughputs(path)
    key = ("ResNet-18 (batch size 32)", 1)
    assert parsed["v100"][key]["null"] == pytest.approx(oracle["v100"][key]["null"])
    pair_key = ("LM (batch size 10)", 1)
    assert parsed["v100"][key][pair_key] == pytest.approx(
        oracle["v100"][key][pair_key]
    )


@pytest.mark.skipif(
    not os.path.exists("/root/reference/scheduler/simulation_throughputs.json"),
    reason="reference oracle unavailable",
)
def test_read_reference_oracle():
    parsed = read_throughputs("/root/reference/scheduler/simulation_throughputs.json")
    assert "v100" in parsed
    some_key = next(iter(parsed["v100"]))
    assert isinstance(some_key, tuple) and isinstance(some_key[1], int)
    assert "null" in parsed["v100"][some_key]


def test_epoch_math():
    assert steps_per_epoch("ResNet-18", 32) == math.ceil(50000 / 32)
    assert num_epochs("ResNet-18", 32, steps_per_epoch("ResNet-18", 32) * 3) == 3
    assert num_epochs("ResNet-18", 32, 1) == 1


def test_accordion_pattern_shape():
    pat = bs_patterns.accordion_pattern("ResNet-18 (batch size 32)", 32, 300)
    assert len(pat) == 300
    # Head critical regime keeps the original batch size.
    assert all(bs == 32 for bs in pat[:10])
    # First 30% of the job is forced critical.
    assert all(bs == 32 for bs in pat[: int(300 * 0.3) + 1])
    # Past 30%, non-critical epochs scale to the model max.
    assert pat[120] == 256
    # Mid-training critical windows drop back to the original size.
    assert all(bs == 32 for bs in pat[150:160])
    assert all(bs == 32 for bs in pat[250:260])
    # Transformer is exempt.
    tpat = bs_patterns.accordion_pattern("Transformer (batch size 64)", 64, 100)
    assert set(tpat) == {64}


def test_gns_pattern_doubling_and_clamp():
    pat = bs_patterns.gns_pattern("LM (batch size 10)", 10, 100, 1)
    assert pat[:11] == [10] * 11
    assert pat[11] == 20 and pat[20] == 20
    assert pat[21] == 40 and pat[40] == 40
    # 8x would be 80 == LM max; clamped at 80.
    assert pat[41] == 80 and pat[98] == 80
    # Reference quirk: last epoch keeps the base size when it falls outside
    # the first breakpoint's range.
    assert pat[99] == 10
    # Below the activation threshold nothing changes.
    short = bs_patterns.gns_pattern("LM (batch size 10)", 10, 11, 1)
    assert set(short) == {10}
    # Unknown (model, bs, sf) combinations stay static.
    static = bs_patterns.gns_pattern("LM (batch size 80)", 80, 100, 1)
    assert set(static) == {80}


def test_profile_synthesis():
    oracle = generate_oracle()
    jobs = [
        Job(
            job_type="ResNet-18 (batch size 32)",
            total_steps=steps_per_epoch("ResNet-18", 32) * 50,
            scale_factor=1,
            mode="gns",
        ),
        Job(
            job_type="LM (batch size 10)",
            total_steps=steps_per_epoch("LM", 10) * 30,
            scale_factor=2,
            mode="accordion",
        ),
    ]
    profiles = synthesize_profiles(jobs, oracle)
    for i, job in enumerate(jobs):
        p = profiles[i]
        assert p["num_epochs"] == num_epochs(job.model, job.batch_size, job.total_steps)
        assert len(p["bs_every_epoch"]) == p["num_epochs"]
        assert len(p["duration_every_epoch"]) == p["num_epochs"]
        assert p["duration"] == pytest.approx(sum(p["duration_every_epoch"]))
        assert all(d > 0 for d in p["duration_every_epoch"])
        assert p["scale_factor"] == job.scale_factor
    # GNS epochs with bigger batches take no longer per sample: fewer steps
    # but lower steps/s roughly cancel; durations must stay positive/finite.
    assert profiles[0]["bs_every_epoch"][0] == 32


@pytest.mark.skipif(not REFERENCE_TRACES, reason="reference traces unavailable")
def test_profiles_for_full_reference_trace():
    oracle = generate_oracle()
    trace = [t for t in REFERENCE_TRACES if t.startswith(
        "/root/reference/scheduler/traces/shockwave/120_"
    )][0]
    jobs, _ = parse_trace(trace)
    profiles = synthesize_profiles(jobs, oracle)
    assert len(profiles) == len(jobs)
    for p in profiles.values():
        assert p["num_epochs"] >= 1
        assert p["duration"] > 0


def test_generate_trace_jobs_deterministic_and_parseable(tmp_path):
    from shockwave_tpu.data.generate import (
        DYNAMIC_MODE_DIST,
        SHOCKWAVE_SCALE_FACTOR_DIST,
        generate_trace_file,
        generate_trace_jobs,
    )

    oracle = generate_oracle()
    kwargs = dict(
        scale_factor_dist=SHOCKWAVE_SCALE_FACTOR_DIST,
        mode_dist=DYNAMIC_MODE_DIST,
    )
    jobs_a, arr_a = generate_trace_jobs(40, oracle, seed=3, lam=100, **kwargs)
    jobs_b, arr_b = generate_trace_jobs(40, oracle, seed=3, lam=100, **kwargs)
    assert arr_a == arr_b
    assert [j.job_type for j in jobs_a] == [j.job_type for j in jobs_b]
    assert [j.total_steps for j in jobs_a] == [j.total_steps for j in jobs_b]

    # Poisson arrivals: start at zero, nondecreasing.
    assert arr_a[0] == 0
    assert all(b >= a for a, b in zip(arr_a, arr_a[1:]))
    # Dynamic style: no static jobs, scale factors from the 60/30/9/1 support.
    assert all(j.mode in ("accordion", "gns") for j in jobs_a)
    assert all(j.scale_factor in (1, 2, 4, 8) for j in jobs_a)
    # Steps follow duration x oracle throughput.
    for job in jobs_a:
        tput = oracle["v100"][(job.job_type, job.scale_factor)]["null"]
        assert job.total_steps == max(1, int(job.duration * tput))

    # Round-trips through the 12-field trace format.
    path = str(tmp_path / "gen.trace")
    generate_trace_file(path, 15, oracle, seed=9, lam=50, **kwargs)
    parsed, arrivals = parse_trace(path)
    assert len(parsed) == 15 and len(arrivals) == 15
    profiles = synthesize_profiles(parsed, oracle)
    assert all(p["num_epochs"] >= 1 for p in profiles.values())


def test_committed_traces_parse_standalone():
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    committed = sorted(glob.glob(os.path.join(repo_root, "traces", "*.trace")))
    assert len(committed) >= 2, "repo must ship standalone traces"
    oracle = generate_oracle()
    for trace in committed:
        jobs, arrivals = parse_trace(trace)
        assert len(jobs) == len(arrivals) > 0
        profiles = synthesize_profiles(jobs, oracle)
        assert len(profiles) == len(jobs)
