"""Smoke tests for the microbenchmark/sweep drivers' core cells (the
full sweeps run offline and commit artifacts under results/)."""

import pytest

pytestmark = pytest.mark.slow

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(rel):
    spec = importlib.util.spec_from_file_location(
        os.path.basename(rel)[:-3], os.path.join(REPO, rel)
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_plan_solve_backends_agree_on_small_instance():
    mod = _load("scripts/microbenchmarks/sweep_plan_solve_runtimes.py")
    problem = mod.make_problem(24, seed=1)
    solvers = mod.backends()
    assert {
        "milp_reference", "milp_tightened", "jax_level", "jax_greedy"
    } <= set(solvers)
    objs = {
        name: problem.objective_value(solve(problem))
        for name, solve in solvers.items()
    }
    ref = objs["milp_reference"]
    for name, o in objs.items():
        assert o >= ref - 0.01 * abs(ref), (name, o, ref)


def test_estimator_sweep_cell_runs_and_degrades_gracefully():
    mod = _load("scripts/sweeps/run_estimator_sweep.py")
    oracle_run = mod.run_cell(mod.DEFAULT_TRACE, "max_min_fairness_packed",
                              8, 1.0, None)
    est_run = mod.run_cell(mod.DEFAULT_TRACE, "max_min_fairness_packed",
                           8, 0.5, 4)
    assert oracle_run["makespan"] > 0 and est_run["makespan"] > 0
    # Estimated throughputs must not blow scheduling quality up: within
    # 25% of the oracle makespan on the committed 12-job trace.
    assert est_run["makespan"] <= 1.25 * oracle_run["makespan"]
