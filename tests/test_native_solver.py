"""The C++ greedy must agree with the JAX greedy (same semantics, host
build via ctypes) on random instances, and plug into the planner."""

import numpy as np
import pytest

from shockwave_tpu import native
from tests.test_shockwave_solver import random_problem

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C++ compiler"
)


@pytest.mark.parametrize("seed", range(6))
def test_matches_jax_greedy_quality(seed):
    from shockwave_tpu.solver.eg_jax import solve_eg_greedy

    rng = np.random.default_rng(seed)
    problem = random_problem(rng, J=8, R=5, num_gpus=4)
    Y_native = native.solve_eg_greedy_native(problem)
    Y_jax = solve_eg_greedy(problem)
    # Feasibility is identical by construction; objectives must agree up
    # to float32-vs-double tie-breaks.
    assert np.all(problem.nworkers @ Y_native <= problem.num_gpus + 1e-9)
    obj_native = problem.objective_value(Y_native)
    obj_jax = problem.objective_value(Y_jax)
    assert obj_native >= obj_jax - 0.02 * max(1.0, abs(obj_jax))


def test_large_instance_runs_fast():
    import time

    rng = np.random.default_rng(0)
    problem = random_problem(rng, J=200, R=20, num_gpus=64)
    start = time.time()
    Y = native.solve_eg_greedy_native(problem)
    elapsed = time.time() - start
    assert np.all(problem.nworkers @ Y <= problem.num_gpus + 1e-9)
    assert elapsed < 5.0


def test_planner_native_backend_end_to_end():
    from tests.test_shockwave_e2e import make_jobs, run_shockwave

    jobs, arrivals = make_jobs(num_jobs=4, epochs=2)
    sched, makespan = run_shockwave("native", jobs, arrivals)
    assert len(sched._job_completion_times) == len(jobs)
    assert makespan > 0


@pytest.mark.parametrize("seed", range(4))
def test_switch_cost_matches_jax_greedy_quality(seed):
    """The C++ greedy optimizes the same preemption-aware extended
    objective as the JAX greedy (keep-incumbent bonus on the first
    granted round)."""
    from tests.test_shockwave_solver import TestSwitchingCost

    problem = TestSwitchingCost().switchy_problem(seed, J=8, R=5, num_gpus=4)
    from shockwave_tpu.solver.eg_jax import solve_eg_greedy

    Y_native = native.solve_eg_greedy_native(problem)
    Y_jax = solve_eg_greedy(problem)
    assert np.all(problem.nworkers @ Y_native <= problem.num_gpus + 1e-9)
    obj_native = problem.objective_value(Y_native)
    obj_jax = problem.objective_value(Y_jax)
    assert obj_native >= obj_jax - 0.02 * max(1.0, abs(obj_jax))
