"""Interprocedural analysis (shockwave_tpu/analysis/project.py +
rules/interproc.py): symbol table / call graph resolution, the three
cross-file rules on a fixture package, the CLI surfaces grown this PR
(--format github, --fix, --lock-graph), and the CI gate's broken-
baseline exit code.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from shockwave_tpu.analysis.core import repo_root, run_paths
from shockwave_tpu.analysis.project import Project
from shockwave_tpu.analysis.rules.interproc import (
    LockOrderCycle,
    SwallowedException,
    TransitiveHostSync,
    lock_graph_dict,
)


def build_project(tmp_path, files):
    """A throwaway package tree -> Project."""
    pkg = tmp_path / "shockwave_tpu"
    for rel, src in files.items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    for dirpath, _, filenames in os.walk(pkg):
        if "__init__.py" not in filenames:
            (pkg / os.path.relpath(dirpath, pkg) / "__init__.py").touch()
    return Project.build(str(tmp_path))


# -- symbol table / call graph ------------------------------------------

class TestProject:
    def test_cross_module_function_resolution(self, tmp_path):
        p = build_project(tmp_path, {
            "a.py": """
                from shockwave_tpu import b

                def caller():
                    b.helper()
            """,
            "b.py": """
                def helper():
                    pass
            """,
        })
        fn = p.functions["shockwave_tpu.a.caller"]
        assert [qn for _, qn in fn.calls] == ["shockwave_tpu.b.helper"]

    def test_self_method_and_base_class_resolution(self, tmp_path):
        p = build_project(tmp_path, {
            "m.py": """
                class Base:
                    def shared(self):
                        pass

                class Child(Base):
                    def go(self):
                        self.shared()
            """,
        })
        fn = p.functions["shockwave_tpu.m.Child.go"]
        assert [qn for _, qn in fn.calls] == ["shockwave_tpu.m.Base.shared"]

    def test_module_instance_method_resolution(self, tmp_path):
        p = build_project(tmp_path, {
            "m.py": """
                class Registry:
                    def inc(self):
                        pass

                _registry = Registry()

                def bump():
                    _registry.inc()
            """,
        })
        fn = p.functions["shockwave_tpu.m.bump"]
        assert [qn for _, qn in fn.calls] == [
            "shockwave_tpu.m.Registry.inc"
        ]

    def test_jit_alias_unwrapping(self, tmp_path):
        p = build_project(tmp_path, {
            "m.py": """
                import jax

                def step(s):
                    return s

                fast_step = jax.jit(step)

                def loop(s):
                    return fast_step(s)
            """,
        })
        fn = p.functions["shockwave_tpu.m.loop"]
        assert [qn for _, qn in fn.calls] == ["shockwave_tpu.m.step"]

    def test_function_local_import_resolution(self, tmp_path):
        p = build_project(tmp_path, {
            "a.py": """
                def caller():
                    from shockwave_tpu import b

                    b.helper()
            """,
            "b.py": """
                def helper():
                    pass
            """,
        })
        fn = p.functions["shockwave_tpu.a.caller"]
        assert [qn for _, qn in fn.calls] == ["shockwave_tpu.b.helper"]


# -- lock-order-cycle ---------------------------------------------------

LOCK_AB = {
    "a.py": """
        import threading
        from shockwave_tpu import b

        class A:
            def __init__(self):
                self._lock = threading.Lock()

            def poke(self):
                with self._lock:
                    b.poke_b()

        _a = A()

        def bump_a():
            with _a._lock:
                pass
    """,
    "b.py": """
        import threading
        from shockwave_tpu import a

        class B:
            def __init__(self):
                self._lock = threading.Lock()

            def kick(self):
                with self._lock:
                    a.bump_a()

        _b = B()

        def poke_b():
            with _b._lock:
                pass
    """,
}


class TestLockOrderCycle:
    def test_ab_ba_cycle_flagged(self, tmp_path):
        p = build_project(tmp_path, LOCK_AB)
        findings = [
            f for f in LockOrderCycle().check_project(p) if not f.suppressed
        ]
        assert any("lock-order cycle" in f.message for f in findings)

    def test_one_direction_is_quiet(self, tmp_path):
        files = dict(LOCK_AB)
        # Remove the reverse edge: B.kick no longer calls back into a.
        files["b.py"] = files["b.py"].replace("a.bump_a()", "pass")
        p = build_project(tmp_path, files)
        findings = [
            f for f in LockOrderCycle().check_project(p) if not f.suppressed
        ]
        assert findings == []

    def test_nonreentrant_self_deadlock_flagged(self, tmp_path):
        p = build_project(tmp_path, {
            "c.py": """
                import threading

                class C:
                    def __init__(self):
                        self._lock = threading.Lock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
            """,
        })
        findings = list(LockOrderCycle().check_project(p))
        assert any("self-deadlock" in f.message for f in findings)

    def test_rlock_reentry_is_quiet(self, tmp_path):
        p = build_project(tmp_path, {
            "c.py": """
                import threading

                class C:
                    def __init__(self):
                        self._lock = threading.RLock()

                    def outer(self):
                        with self._lock:
                            self.inner()

                    def inner(self):
                        with self._lock:
                            pass
            """,
        })
        assert list(LockOrderCycle().check_project(p)) == []

    def test_sanitize_factory_locks_are_seen(self, tmp_path):
        """Locks created via the sanitizer factories participate in the
        graph exactly like raw threading primitives."""
        files = {
            rel: src.replace(
                "threading.Lock()", 'sanitize.make_lock("x")'
            ).replace("import threading", "from shockwave_tpu.analysis import sanitize")
            for rel, src in LOCK_AB.items()
        }
        p = build_project(tmp_path, files)
        findings = list(LockOrderCycle().check_project(p))
        assert any("lock-order cycle" in f.message for f in findings)

    def test_repo_lock_graph_has_edges_and_no_cycle(self):
        """The real repo: the obs facade edges exist (the analysis sees
        through module-level instances and local imports) and the graph
        is acyclic — guarded by the tier-1 baseline gate staying empty."""
        graph = lock_graph_dict(Project.build(repo_root()))
        pairs = {(e["held"], e["acquired"]) for e in graph["edges"]}
        assert (
            "runtime.dispatcher.Dispatcher._lock",
            "obs.metrics.MetricsRegistry._lock",
        ) in pairs
        assert (
            "obs.watchdog.Watchdog._lock",
            "obs.metrics.MetricsRegistry._lock",
        ) in pairs
        for a, b in pairs:
            assert (b, a) not in pairs, f"cycle {a} <-> {b}"
        assert graph["self_deadlocks"] == []


# -- transitive-host-sync -----------------------------------------------

class TestTransitiveHostSync:
    def test_cross_file_item_in_jit_loop_flagged(self, tmp_path):
        p = build_project(tmp_path, {
            "m.py": """
                import jax
                from shockwave_tpu import util

                def train(state, batches):
                    jit_step = jax.jit(step_fn)
                    for batch in batches:
                        state = jit_step(state, batch)
                        util.log_loss(state)
                    return state

                def step_fn(state, batch):
                    return state
            """,
            "util.py": """
                def log_loss(state):
                    return record(state)

                def record(state):
                    return state.loss.item()
            """,
        })
        findings = [
            f
            for f in TransitiveHostSync().check_project(p)
            if not f.suppressed
        ]
        assert len(findings) == 1
        f = findings[0]
        assert f.path == "shockwave_tpu/m.py"
        assert ".item()" in f.message and "util.py" in f.message

    def test_declared_host_boundary_is_exempt(self, tmp_path):
        p = build_project(tmp_path, {
            "m.py": """
                import jax
                from shockwave_tpu import util

                def train(state, batches):
                    jit_step = jax.jit(step_fn)
                    for batch in batches:
                        state = jit_step(state, batch)
                        util.fetch(state)

                def step_fn(state, batch):
                    return state
            """,
            "util.py": """
                def fetch(state):
                    \"\"\"Deliberate host-side fetch of the final value.\"\"\"
                    return state.loss.item()
            """,
        })
        assert list(TransitiveHostSync().check_project(p)) == []

    def test_same_function_sync_left_to_per_file_rule(self, tmp_path):
        p = build_project(tmp_path, {
            "m.py": """
                import jax

                def helper(x):
                    return x

                def train(state, batches):
                    jit_step = jax.jit(step_fn)
                    for batch in batches:
                        state = jit_step(state, batch)
                        print(state.loss.item())

                def step_fn(state, batch):
                    return state
            """,
        })
        # The direct .item() is the per-file host-sync-in-hot-loop
        # rule's finding; the transitive rule must not duplicate it.
        assert list(TransitiveHostSync().check_project(p)) == []

    def test_plain_alias_is_not_a_hot_region(self, tmp_path):
        """`public = _impl` / lru_cache aliases must not mark the
        target as traced — only jit/remat wrappers do."""
        p = build_project(tmp_path, {
            "m.py": """
                import functools

                from shockwave_tpu import util

                def _impl(x):
                    return util.polish(x)

                main = _impl
                cached = functools.lru_cache(_impl)
            """,
            "util.py": """
                import numpy as np

                def polish(x):
                    return np.asarray(x)
            """,
        })
        assert list(TransitiveHostSync().check_project(p)) == []

    def test_reachable_from_jitted_function_body(self, tmp_path):
        p = build_project(tmp_path, {
            "m.py": """
                import functools

                import jax
                from shockwave_tpu import util

                @functools.partial(jax.jit, static_argnames=("n",))
                def solve(x, n):
                    return util.polish(x)
            """,
            "util.py": """
                import numpy as np

                def polish(x):
                    return np.asarray(x)
            """,
        })
        findings = list(TransitiveHostSync().check_project(p))
        assert len(findings) == 1
        assert "np.asarray" in findings[0].message


# -- swallowed-exception ------------------------------------------------

class TestSwallowedException:
    def check(self, tmp_path, body):
        p = build_project(tmp_path, {"runtime/r.py": body})
        return [
            f
            for f in SwallowedException().check_project(p)
            if not f.suppressed
        ]

    def test_pass_handler_flagged(self, tmp_path):
        findings = self.check(tmp_path, """
            def rpc():
                try:
                    send()
                except Exception:
                    pass
        """)
        assert len(findings) == 1

    def test_bare_except_flagged(self, tmp_path):
        findings = self.check(tmp_path, """
            def rpc():
                try:
                    send()
                except:
                    result = None
        """)
        assert len(findings) == 1

    def test_logging_handler_ok(self, tmp_path):
        findings = self.check(tmp_path, """
            import logging

            LOG = logging.getLogger("r")

            def rpc():
                try:
                    send()
                except Exception:
                    LOG.warning("send failed", exc_info=True)
        """)
        assert findings == []

    def test_delegated_logging_ok(self, tmp_path):
        findings = self.check(tmp_path, """
            import logging

            LOG = logging.getLogger("r")

            def _report(e):
                LOG.error("failed: %s", e)

            def rpc():
                try:
                    send()
                except Exception as e:
                    _report(e)
        """)
        assert findings == []

    def test_counter_increment_ok(self, tmp_path):
        findings = self.check(tmp_path, """
            from shockwave_tpu import obs

            def rpc():
                try:
                    send()
                except Exception:
                    obs.counter("rpc_errors_total", "").inc()
        """)
        assert findings == []

    def test_reraise_ok(self, tmp_path):
        findings = self.check(tmp_path, """
            def rpc():
                try:
                    send()
                except Exception:
                    raise
        """)
        assert findings == []

    def test_typed_handler_not_flagged(self, tmp_path):
        findings = self.check(tmp_path, """
            def rpc():
                try:
                    send()
                except ProcessLookupError:
                    pass
        """)
        assert findings == []

    def test_out_of_scope_module_not_flagged(self, tmp_path):
        p = build_project(tmp_path, {"models/m.py": """
            def anything():
                try:
                    send()
                except Exception:
                    pass
        """})
        assert list(SwallowedException().check_project(p)) == []

    def test_suppression_respected(self, tmp_path):
        findings = self.check(tmp_path, """
            def rpc():
                try:
                    send()
                # best-effort teardown, failures expected
                # shockwave-lint: disable=swallowed-exception
                except Exception:
                    pass
        """)
        assert findings == []


# -- CLI + gate surfaces ------------------------------------------------

BAD_WRITER = """\
import json


def leak(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f, indent=2)
"""


class TestCliSurfaces:
    def run_cli(self, *argv):
        return subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis", *argv],
            capture_output=True,
            text=True,
            cwd=repo_root(),
            timeout=300,
        )

    def test_github_format_annotations(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_WRITER)
        proc = self.run_cli(
            "--format", "github", "--no-baseline", str(bad)
        )
        assert proc.returncode == 1
        line = [
            l for l in proc.stdout.splitlines() if l.startswith("::error ")
        ][0]
        assert "line=5" in line
        assert "title=shockwave-lint non-atomic-artifact-write" in line

    def test_fix_dry_run_then_apply(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_WRITER)
        dry = self.run_cli("--fix", "--dry-run", str(bad))
        assert dry.returncode == 0
        assert "atomic_write_json(path, obj, indent=2)" in dry.stdout
        assert bad.read_text() == BAD_WRITER  # nothing written
        applied = self.run_cli("--fix", str(bad))
        assert applied.returncode == 0
        fixed = bad.read_text()
        assert "atomic_write_json(path, obj, indent=2)" in fixed
        assert "from shockwave_tpu.utils.fileio import atomic_write_json" in fixed
        compile(fixed, str(bad), "exec")  # still valid python
        # Idempotent: nothing left to fix.
        again = self.run_cli("--fix", str(bad))
        assert "0 rewrite(s) applied" in again.stdout

    def test_fix_leaves_extra_open_args_alone(self, tmp_path):
        """An encoding/newline argument has no slot on the atomic
        helpers; the fixer must skip rather than change the bytes."""
        src = (
            "def save(path, text):\n"
            '    with open(path, "w", encoding="latin-1") as f:\n'
            "        f.write(text)\n"
        )
        f = tmp_path / "enc.py"
        f.write_text(src)
        proc = self.run_cli("--fix", str(f))
        assert "0 rewrite(s) applied" in proc.stdout
        assert f.read_text() == src

    def test_lock_graph_dump(self):
        proc = self.run_cli("--lock-graph")
        assert proc.returncode == 0
        graph = json.loads(proc.stdout)
        assert any(
            e["acquired"] == "obs.metrics.MetricsRegistry._lock"
            for e in graph["edges"]
        )

    def test_partial_run_does_not_report_foreign_stale(self, tmp_path):
        """A --changed-only-style subset run must not call baseline
        entries for unchecked files stale."""
        from shockwave_tpu.analysis import cli

        baseline = tmp_path / "baseline.json"
        baseline.write_text(json.dumps({
            "entries": [{
                "fingerprint": "feedfeedfeedfeed",
                "rule": "non-atomic-artifact-write",
                "path": "scripts/unrelated.py",
                "line": 1,
                "line_text": "x",
            }]
        }))
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        rc = cli.main([str(clean), "--baseline", str(baseline)])
        assert rc == 0  # stale entry is for a file we did not check


class TestLintGate:
    def _load_gate(self):
        import importlib.util

        path = os.path.join(repo_root(), "scripts", "ci", "lint.py")
        spec = importlib.util.spec_from_file_location("lint_gate", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_missing_baseline_is_broken_gate(self, tmp_path):
        gate = self._load_gate()
        gate.BASELINE = str(tmp_path / "nope.json")
        assert "missing" in gate._check_baseline_readable()

    def test_unparseable_baseline_is_broken_gate(self, tmp_path):
        gate = self._load_gate()
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        gate.BASELINE = str(bad)
        assert "does not parse" in gate._check_baseline_readable()

    def test_entriesless_baseline_is_broken_gate(self, tmp_path):
        gate = self._load_gate()
        bad = tmp_path / "noentries.json"
        bad.write_text("[]")
        gate.BASELINE = str(bad)
        assert "entries" in gate._check_baseline_readable()

    def test_committed_baseline_is_readable(self):
        gate = self._load_gate()
        assert gate._check_baseline_readable() == ""

    def test_changed_only_lists_scoped_python_files(self):
        gate = self._load_gate()
        try:
            changed = gate._changed_python_files()
        except Exception:
            pytest.skip("git unavailable")
        assert all(p.endswith(".py") for p in changed)
        assert all(
            p.startswith(("shockwave_tpu/", "scripts/")) or p == "bench.py"
            for p in changed
        )


def test_repo_interprocedural_rules_clean():
    """The three cross-file rules over the real repo: the PR-6 sweep
    fixed every finding, so the ratchet starts (and stays) empty."""
    findings = [
        f
        for f in run_paths(
            rules=[
                LockOrderCycle(),
                TransitiveHostSync(),
                SwallowedException(),
            ]
        )
        if not f.suppressed
    ]
    assert findings == [], [f.render() for f in findings]
