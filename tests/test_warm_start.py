"""Solver warm start: the persisted serialized executable must load in
a process that didn't compile it (simulated via a cleared memo) and
produce bit-identical counts/objective to the jitted path."""

import numpy as np
import pytest

from shockwave_tpu.solver import warm_start
from shockwave_tpu.solver.eg_jax import num_slots_for, solve_level_counts
from shockwave_tpu.solver.eg_problem import EGProblem


def _problem(num_jobs=40, future_rounds=8, num_gpus=16, seed=0):
    rng = np.random.default_rng(seed)
    total = rng.integers(5, 60, num_jobs).astype(float)
    completed = np.floor(total * rng.uniform(0, 0.8, num_jobs))
    epoch_dur = rng.uniform(60, 2000, num_jobs)
    return EGProblem(
        priorities=rng.uniform(0.5, 30.0, num_jobs),
        completed_epochs=completed,
        total_epochs=total,
        epoch_duration=epoch_dur,
        remaining_runtime=(total - completed) * epoch_dur,
        nworkers=rng.choice([1, 1, 2], num_jobs).astype(float),
        num_gpus=num_gpus,
        round_duration=120.0,
        future_rounds=future_rounds,
        regularizer=10.0,
        log_bases=np.array([0.0, 0.2, 0.4, 0.6, 0.8, 1.0]),
    )


@pytest.fixture
def isolated_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("SHOCKWAVE_SOLVER_CACHE_DIR", str(tmp_path))
    saved = dict(warm_start._LOADED)
    warm_start._LOADED.clear()
    yield str(tmp_path)
    warm_start._LOADED.clear()
    warm_start._LOADED.update(saved)


def test_warm_then_load_is_bit_identical(isolated_cache):
    problem = _problem()
    slots = num_slots_for(problem.num_jobs)

    # No blob yet: the jitted path runs and load() reports a miss.
    assert warm_start.load(slots, 8, 64, False) is None
    counts_ref, obj_ref = solve_level_counts(problem)

    # warm() itself must drop the negative cache the miss above left
    # behind, so the fast path engages without a process restart.
    paths = warm_start.warm(slots=slots, future_rounds=8)
    assert len(paths) == 2  # with and without the switch-cost bonus
    compiled = warm_start.load(slots, 8, 64, False)
    assert compiled is not None

    counts, obj = solve_level_counts(problem)
    assert np.array_equal(counts, counts_ref)
    assert obj == obj_ref
    # ...and via the FAST path, not the silent jitted fallback: a
    # call-time failure would have negatively cached the signature
    # (warm_start.invalidate) before falling back to bit-identical
    # results, masking a total cold-start regression.
    key = warm_start.cache_key(slots, 8, 64, False)
    assert warm_start._LOADED.get(key) is not None, (
        "precompiled executable was invalidated at call time; the "
        "solve silently fell back to the jitted path"
    )


def test_corrupt_blob_falls_back_to_jit(isolated_cache):
    problem = _problem(seed=1)
    slots = num_slots_for(problem.num_jobs)
    key = warm_start.cache_key(slots, 8, 64, False)
    path = warm_start._blob_path(key)
    import os

    os.makedirs(warm_start.cache_dir(), exist_ok=True)
    with open(path, "wb") as f:
        f.write(b"not a pickled executable")
    assert warm_start.load(slots, 8, 64, False) is None
    assert not os.path.exists(path), "corrupt blob must be removed"
    counts, obj = solve_level_counts(problem)  # jitted fallback
    assert counts.shape == (problem.num_jobs,)
    assert np.isfinite(obj)


def test_cache_key_tracks_solver_source_and_shape():
    k = warm_start.cache_key(1024, 50, 64, True)
    assert k == warm_start.cache_key(1024, 50, 64, True)
    assert k != warm_start.cache_key(1024, 50, 64, False)
    assert k != warm_start.cache_key(512, 50, 64, True)
    assert k != warm_start.cache_key(1024, 40, 64, True)
    assert k != warm_start.cache_key(1024, 50, 64, True, num_bases=7)
